"""Data pipeline (reference: python/paddle/io — DataLoader at io/reader.py:216,
Dataset/Sampler/BatchSampler under io/dataloader/).

TPU-native notes: batches are assembled host-side as numpy and transferred once
per step (minimizing host->device traffic). num_workers > 0 forks worker
PROCESSES (fetch/transform/collate off the parent's GIL) with ordered
delivery, fault propagation, and optional POSIX shared-memory batch transport
(use_shared_memory, like the reference's shm ring); iterable datasets and
non-CPU-initialized backends fall back to a thread prefetcher.
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Iterable, Sequence

import numpy as np

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.io.device_feed import (BatchSpecCache, DeviceFeeder,
                                       DispatchWindow, LossFuture,
                                       prefetch_to_device)
from paddle_tpu.io.packing import (SequencePacker, pack_examples,
                                   packing_stats, pad_examples, unpack_batch)
from paddle_tpu.ops.random_state import default_generator

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split", "Sampler",
    "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
    "SubsetRandomSampler", "BatchSampler", "DistributedBatchSampler",
    "DataLoader", "default_collate_fn", "get_worker_info",
    "DeviceFeeder", "prefetch_to_device", "BatchSpecCache", "DispatchWindow",
    "LossFuture", "SequencePacker", "pack_examples", "pad_examples",
    "packing_stats", "unpack_batch",
]


def _as_rng(generator):
    """Thread a reproducibility handle through samplers/splits: None -> the
    global numpy RNG (legacy behavior), an int -> a fresh seeded Generator,
    a numpy Generator/RandomState passes through (its state advances across
    uses, the torch generator semantics)."""
    if generator is None:
        return np.random
    if isinstance(generator, (int, np.integer)):
        return np.random.default_rng(int(generator))
    return generator


def _rand_ints(rng, n, size):
    # Generator spells it `integers`, RandomState/module spell it `randint`
    if hasattr(rng, "integers"):
        return rng.integers(0, n, size)
    return rng.randint(0, n, size)


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(np.asarray(t._value)[idx] if isinstance(t, Tensor) else t[idx] for t in self.tensors)

    def __len__(self):
        t = self.tensors[0]
        return len(t) if not isinstance(t, Tensor) else t.shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        return itertools.chain(*self.datasets)


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    if sum(lengths) != n:
        # fraction support
        if all(0 < l < 1 for l in lengths):
            lengths = [int(l * n) for l in lengths]
            lengths[-1] = n - sum(lengths[:-1])
        else:
            raise ValueError("sum of lengths must equal dataset size")
    perm = _as_rng(generator).permutation(n)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off : off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)
        self.generator = generator

    def __iter__(self):
        n = len(self.data_source)
        rng = _as_rng(self.generator)
        if self.replacement:
            return iter(_rand_ints(rng, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    """reference io/dataloader/sampler.py WeightedRandomSampler: draw indices
    with probability proportional to `weights`."""

    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        if (self.weights < 0).any():
            raise ValueError("weights must be non-negative")
        self.num_samples = int(num_samples)
        self.replacement = bool(replacement)
        if not self.replacement and self.num_samples > len(self.weights):
            raise ValueError("num_samples exceeds population without replacement")

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    """reference ConcatDataset: datasets glued end to end."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __getitem__(self, idx):
        if idx < 0:
            idx += self.cum[-1]
        di = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if di == 0 else self.cum[di - 1]
        return self.datasets[di][idx - prev]

    def __len__(self):
        return self.cum[-1]


class _WorkerInfo:
    def __init__(self, id_, num_workers, dataset=None):
        self.id = id_
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    """Inside a DataLoader worker process returns (id, num_workers); None in
    the main process (reference io/dataloader/worker.py get_worker_info)."""
    return _worker_info



class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded sampler (reference: io/dataloader/batch_sampler.py
    DistributedBatchSampler): each rank sees a strided shard of the indices."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from paddle_tpu.distributed import get_rank, get_world_size

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        # pad to a multiple of nranks so every rank gets equal batches
        total = int(np.ceil(n / self.nranks)) * self.nranks
        indices = np.concatenate([indices, indices[: total - n]])
        local = indices[self.rank :: self.nranks]
        batch = []
        for idx in local.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        per_rank = int(np.ceil(len(self.dataset) / self.nranks))
        if self.drop_last:
            return per_rank // self.batch_size
        return (per_rank + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch):
    """Stack samples into batched Tensors (reference: io/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return tuple(default_collate_fn([b[i] for b in batch]) for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return Tensor(jnp.stack([s._value for s in batch]))
    arr = np.stack([np.asarray(s) for s in batch])
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return Tensor(jnp.asarray(arr))


class _PrefetchIter:
    def __init__(self, it, num_prefetch):
        from paddle_tpu.io.device_feed import THREAD_PREFIX, interruptible_put

        self.q: queue.Queue = queue.Queue(maxsize=num_prefetch)
        self._sentinel = object()
        self._err = None
        self._stop = threading.Event()

        def worker():
            try:
                for item in it:
                    if not interruptible_put(self.q, item, self._stop):
                        return
            except BaseException as e:  # propagate to consumer
                self._err = e
            finally:
                interruptible_put(self.q, self._sentinel, self._stop)

        self._t = threading.Thread(target=worker, daemon=True,
                                   name=f"{THREAD_PREFIX}.prefetch")
        self._t.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item = self.q.get()
        if item is self._sentinel:
            err = self._err
            self.close()
            if err is not None:
                self._err = None
                raise err
            raise StopIteration
        return item

    def close(self):
        from paddle_tpu.io.device_feed import stop_and_join

        stop_and_join(self.q, self._stop, self._t)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _collate_np(batch):
    """Worker-side collate to plain numpy (no jax in child processes; the
    parent converts to Tensors). Mirrors default_collate_fn's structure."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return tuple(_collate_np([b[i] for b in batch]) for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: _collate_np([b[k] for b in batch]) for k in sample}
    arr = np.stack([np.asarray(s) for s in batch])
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return arr


def _np_to_tensor_tree(x):
    import jax

    if isinstance(x, tuple):
        return tuple(_np_to_tensor_tree(v) for v in x)
    if isinstance(x, dict):
        return {k: _np_to_tensor_tree(v) for k, v in x.items()}
    if isinstance(x, np.ndarray):
        return Tensor(jnp.asarray(x))
    if isinstance(x, jax.Array):  # shm-imported leaves arrive device-ready
        return Tensor(x)
    return x


def _fork_workers_safe() -> bool:
    """Forking is only safe before the XLA backend initializes or when the
    backend is CPU-only: a forked child inheriting an initialized TPU client
    can hang (same restriction as the reference's CUDA-tensor-in-worker
    rule). Unsafe configs degrade to the thread prefetcher with a warning."""
    try:
        from jax._src import xla_bridge

        if not xla_bridge._backends:
            return True
        import jax as _jax

        return all(d.platform == "cpu" for d in _jax.devices())
    except Exception:
        return False  # fail closed: introspection failure -> thread prefetcher


class _ShmRef:
    """Placeholder for an array parked in a POSIX shared-memory segment —
    a distinct type, so it is recognizable at ANY nesting depth and can never
    be confused with a container tuple."""

    __slots__ = ("name", "shape", "dtype")

    def __init__(self, name, shape, dtype):
        self.name = name
        self.shape = shape
        self.dtype = dtype

    def __reduce__(self):
        return (_ShmRef, (self.name, self.shape, self.dtype))


def _shm_tree_map(tree, fn):
    if isinstance(tree, tuple):
        return tuple(_shm_tree_map(v, fn) for v in tree)
    if isinstance(tree, list):
        return [_shm_tree_map(v, fn) for v in tree]
    if isinstance(tree, dict):
        return {k: _shm_tree_map(v, fn) for k, v in tree.items()}
    return fn(tree)


def _shm_export(tree, prefix="", counter=None):
    """Move the numpy leaves of a collated batch (any tuple/list/dict
    nesting) into POSIX shared memory; the parent maps the segments instead
    of unpickling array bytes through the queue pipe (reference:
    use_shared_memory=True, core _array_to_share_memory_tensor).
    Segments carry a job-unique name prefix so the parent can sweep strays
    after an abnormal worker death. ENOSPC (tiny /dev/shm) and structured
    dtypes fall back to the pickle path per-leaf; partial export failures
    unlink every already-created segment."""
    from multiprocessing import shared_memory

    names = []

    def export(v):
        if (isinstance(v, np.ndarray) and v.nbytes >= 1024
                and v.dtype.names is None and not v.dtype.hasobject):
            if counter is not None:
                counter[0] += 1
            name = (f"{prefix}{counter[0]}"
                    if (prefix and counter is not None) else None)
            try:
                seg = shared_memory.SharedMemory(name=name, create=True,
                                                 size=v.nbytes)
            except OSError:
                return v  # shm exhausted/unavailable: ship via pickle
            names.append(seg.name)
            np.ndarray(v.shape, v.dtype, buffer=seg.buf)[...] = v
            # the PARENT owns the segment's lifetime: stop this process's
            # resource_tracker from unlinking it at worker exit
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(seg._name, "shared_memory")
            except Exception:
                pass
            seg.close()
            return _ShmRef(seg.name, v.shape, v.dtype.str)
        return v

    try:
        return _shm_tree_map(tree, export)
    except Exception:
        for n in names:
            try:
                seg = shared_memory.SharedMemory(name=n)
                seg.close()
                seg.unlink()
            except Exception:
                pass
        raise


def _shm_import(tree):
    """Parent side: map each segment, move it ONCE into the XLA host buffer
    (jnp.asarray), then unlink — no intermediate numpy copy. Returns
    (tree, n_refs_consumed)."""
    from multiprocessing import shared_memory

    count = [0]

    def imp(v):
        if isinstance(v, _ShmRef):
            count[0] += 1
            seg = shared_memory.SharedMemory(name=v.name)
            try:
                view = np.ndarray(v.shape, np.dtype(v.dtype), buffer=seg.buf)
                # copy=True is load-bearing: the CPU backend zero-copy
                # aliases aligned numpy buffers, and the segment is about to
                # be unlinked
                arr = jnp.array(view, copy=True)
                arr.block_until_ready()
                return arr
            finally:
                seg.close()
                seg.unlink()
        return v

    return _shm_tree_map(tree, imp), count[0]


def _shm_release(tree):
    """Unlink a batch's segments without reading them (early-stop/error
    teardown: nothing else will — the workers unregistered their trackers)."""
    from multiprocessing import shared_memory

    def rel(v):
        if isinstance(v, _ShmRef):
            try:
                seg = shared_memory.SharedMemory(name=v.name)
                seg.close()
                seg.unlink()
            except Exception:
                pass
        return v

    _shm_tree_map(tree, rel)


def _worker_loop(dataset, index_q, result_q, collate, worker_init_fn, wid,
                 use_shared_memory=False, shm_prefix="", num_workers_total=1):
    """Child process: fetch+transform+collate — the Python-heavy work that
    would serialize on the parent's GIL (reference io/dataloader/worker.py)."""
    global _worker_info
    _worker_info = _WorkerInfo(wid, num_workers_total, dataset)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    seq = [0]
    while True:
        item = index_q.get()
        if item is None:
            break
        bid, idxs = item
        try:
            batch = collate([dataset[i] for i in idxs])
            if use_shared_memory:
                batch = _shm_export(batch, f"{shm_prefix}w{wid}_", seq)
            try:
                result_q.put((bid, batch, None))
            except Exception:
                if use_shared_memory:
                    _shm_release(batch)
                raise
        except Exception:
            import traceback

            result_q.put((bid, None, traceback.format_exc()))


class _MultiprocessIter:
    """Process-worker iterator (reference reader.py:216 + worker.py): batch
    index lists fan out to `num_workers` forked children; collated numpy
    batches come back over a result queue and are yielded IN ORDER (out-of-
    order arrivals buffered), converted to Tensors in the parent."""

    def __init__(self, loader):
        import multiprocessing as mp

        ctx = mp.get_context("fork")
        self._collate_user = loader.collate_fn is not default_collate_fn
        collate = loader.collate_fn if self._collate_user else _collate_np
        # shared memory only applies to the numpy default-collate layout
        self._use_shm = bool(getattr(loader, "use_shared_memory", False)
                             and not self._collate_user)
        import os as _os

        self._shm_prefix = f"ptdl_{_os.getpid()}_{id(self) & 0xffff:x}_"
        self.shm_batches = 0  # diagnostics
        self._index_q = ctx.Queue()
        self._result_q = ctx.Queue()
        self._timeout = loader.timeout or None
        self._workers = []
        for wid in range(loader.num_workers):
            w = ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self._index_q, self._result_q, collate,
                      loader.worker_init_fn, wid, self._use_shm,
                      self._shm_prefix, loader.num_workers),
                daemon=True)
            w.start()
            self._workers.append(w)

        self._batches = list(loader.batch_sampler)
        self._next_dispatch = 0
        self._next_yield = 0
        self._pending = {}
        self._inflight_max = loader.num_workers * loader.prefetch_factor
        self._dispatch()

    def _dispatch(self):
        while (self._next_dispatch < len(self._batches)
               and self._next_dispatch - self._next_yield < self._inflight_max):
            self._index_q.put((self._next_dispatch, self._batches[self._next_dispatch]))
            self._next_dispatch += 1

    def __iter__(self):
        return self

    def __next__(self):
        import queue as _q
        import time as _time

        if self._next_yield >= len(self._batches):
            self._shutdown()
            raise StopIteration
        deadline = _time.time() + self._timeout if self._timeout else None
        while self._next_yield not in self._pending:
            try:
                bid, batch, err = self._result_q.get(timeout=1.0)
            except _q.Empty:
                dead = [w for w in self._workers if not w.is_alive()]
                if dead:
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader worker died (exitcode "
                        f"{dead[0].exitcode}) before returning a batch")
                if deadline is not None and _time.time() > deadline:
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader timed out after {self._timeout}s waiting "
                        f"for batch {self._next_yield}")
                continue
            if err is not None:
                self._shutdown()
                raise RuntimeError(f"DataLoader worker failed:\n{err}")
            self._pending[bid] = batch
        batch = self._pending.pop(self._next_yield)
        self._next_yield += 1
        self._dispatch()
        if self._collate_user:
            return batch
        if self._use_shm:
            batch, n_refs = _shm_import(batch)
            self.shm_batches += n_refs > 0
        return _np_to_tensor_tree(batch)

    def _shutdown(self):
        for _ in self._workers:
            try:
                self._index_q.put(None)
            except Exception:
                pass
        for w in self._workers:
            w.join(timeout=5)
            if w.is_alive():
                w.terminate()
        self._workers = []
        if self._use_shm:
            # release in-flight segments: the workers unregistered their
            # trackers, so undelivered batches would otherwise leak in shm
            import queue as _q

            for batch in self._pending.values():
                _shm_release(batch)
            self._pending = {}
            while True:
                try:
                    _, batch, err = self._result_q.get_nowait()
                except (_q.Empty, OSError, ValueError):
                    break
                if err is None:
                    _shm_release(batch)
            # sweep strays from abnormally-died workers (their refs never
            # reached the queue; names carry this loader's unique prefix)
            import glob as _glob

            for path in _glob.glob(f"/dev/shm/{self._shm_prefix}*"):
                try:
                    from multiprocessing import shared_memory as _sm

                    seg = _sm.SharedMemory(name=path.rsplit("/", 1)[1])
                    seg.close()
                    seg.unlink()
                except Exception:
                    pass

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass


class DataLoader:
    """reference: python/paddle/io/reader.py:216."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        if isinstance(dataset, IterableDataset):
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    def _iter_batches(self):
        if self.batch_sampler is None:
            # iterable dataset: chunk the stream
            batch = []
            for item in self.dataset:
                batch.append(item)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        for idxs in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in idxs])

    def __iter__(self):
        if self.num_workers > 0 and self.batch_sampler is not None:
            # map-style + workers: true worker PROCESSES (fetch/transform/
            # collate off the parent's GIL). Iterable datasets keep the
            # thread prefetcher (stream order can't be index-dispatched).
            if _fork_workers_safe():
                return _MultiprocessIter(self)
            import warnings

            warnings.warn(
                "num_workers > 0 with an initialized non-CPU XLA backend: "
                "fork is unsafe, using the thread prefetcher instead")
        it = self._iter_batches()
        if self.num_workers > 0:
            return _PrefetchIter(it, self.num_workers * self.prefetch_factor)
        return it

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("length of IterableDataset loader is unknown")
        return len(self.batch_sampler)
