"""Asynchronous device feed: double-buffered host->device prefetch + bounded
async step dispatch.

Reference analog: the buffered reader + async executor pair that keeps the
device busy between steps (reference reader.py's buffered decorator feeding
the StandaloneExecutor). TPU-native restatement of the tf.data
"prefetch-to-device" idiom: JAX already dispatches the compiled step
asynchronously, so the only things that can serialize a training loop are
  1. host work on the critical path — fetch, transform, collate, and the
     per-input `jax.device_put` that `CompiledTrainStep.__call__` used to
     redo (spec trimming included) for every batch, and
  2. a device->host sync per step — every `float(loss)` blocks until the
     step finishes, collapsing the run-ahead window to zero.
This module removes both:
  * `DeviceFeeder` / `prefetch_to_device` run fetch+collate+sharded placement
    on a background thread with a bounded in-flight queue (depth batches of
    HBM, the double-buffer), propagating worker exceptions to the consumer
    and joining the thread on close;
  * `BatchSpecCache` computes the per-dim divisibility-trimmed
    `NamedSharding` for each input ONCE per batch signature (shapes+dtypes),
    not per step;
  * `DispatchWindow` bounds run-ahead to ~2 steps in flight (blocking on the
    loss of step N-w before admitting step N), so async dispatch cannot pile
    un-executed programs' batches up in HBM;
  * `LossFuture` defers the device->host loss read so callers fetch metrics
    every k steps (`FLAGS_metrics_sync_every`) instead of every step.
"""
from __future__ import annotations

import collections
import queue
import threading
from typing import Iterable, Iterator

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.resilience import faults
from paddle_tpu.profiler import RecordEvent

__all__ = ["DeviceFeeder", "FeederWorkerError", "prefetch_to_device",
           "BatchSpecCache", "LossFuture", "DispatchWindow",
           "default_batch_spec", "trim_batch_spec"]

faults.register(
    "feeder.collate",
    "DeviceFeeder worker crash during fetch/collate of the next batch "
    "(a dataset/transform bug or a dying storage mount)")
faults.register(
    "feeder.device_put",
    "DeviceFeeder worker crash during the sharded host->device placement "
    "of a collated batch")

# thread-name prefix shared by every io/reader background thread: the test
# suite's thread-hygiene guard keys on it to detect leaked prefetchers
THREAD_PREFIX = "paddle_tpu.io"


def interruptible_put(q: queue.Queue, item, stop: threading.Event,
                      poll: float = 0.05) -> bool:
    """Bounded put that stays interruptible: a producer blocked on a full
    queue re-checks `stop` every `poll` seconds, so an abandoned consumer's
    close() unblocks it instead of stranding the thread. Shared by
    DeviceFeeder, the DataLoader thread prefetcher, and reader.buffered."""
    while not stop.is_set():
        try:
            q.put(item, timeout=poll)
            return True
        except queue.Full:
            continue
    return False


def stop_and_join(q: queue.Queue, stop: threading.Event,
                  thread: threading.Thread, timeout: float = 5.0):
    """Producer-thread teardown: signal stop, drain the queue so a blocked
    put wakes, then JOIN the thread (the no-leaked-prefetchers contract the
    conftest thread-hygiene guard enforces)."""
    stop.set()
    try:
        while True:
            q.get_nowait()
    except queue.Empty:
        pass
    if thread.is_alive():
        thread.join(timeout=timeout)


def default_batch_spec(mesh: Mesh | None) -> PartitionSpec:
    """The CompiledTrainStep default input layout: batch dim 0 over every
    data-like axis present in the mesh, the SEQUENCE dim over 'sep'
    (context parallelism) when active."""
    if mesh is None:
        return PartitionSpec()
    data_axes = tuple(a for a in ("dp", "sharding")
                      if a in mesh.shape and mesh.shape[a] > 1)
    sep_on = "sep" in mesh.shape and mesh.shape["sep"] > 1
    return PartitionSpec(data_axes if data_axes else None,
                         "sep" if sep_on else None)


def trim_batch_spec(spec: PartitionSpec, shape, mesh: Mesh) -> PartitionSpec:
    """Per-dim: trim `spec` to this input's rank and drop any dim whose size
    doesn't divide its mesh axes (replicate it instead of crashing on a
    trailing partial batch)."""
    dims = list(tuple(spec))[: len(shape)]
    eff = []
    for d, entry in enumerate(dims):
        axes = [a for a in (entry if isinstance(entry, tuple) else (entry,))
                if a]
        div = 1
        for a in axes:
            div *= int(mesh.shape[a])
        eff.append(entry if (div > 1 and shape[d] % div == 0) or div == 1
                   else None)
    return PartitionSpec(*eff) if len(shape) else PartitionSpec()


def _tree_map(tree, fn):
    if isinstance(tree, (tuple, list)):
        return type(tree)(_tree_map(v, fn) for v in tree)
    if isinstance(tree, dict):
        return {k: _tree_map(v, fn) for k, v in tree.items()}
    return fn(tree)


class BatchSpecCache:
    """Trimmed per-input NamedShardings, computed once per batch SIGNATURE
    (the tuple of leaf shapes+dtypes) instead of once per step. Training
    loops see one or two signatures total (steady batches + one trailing
    partial), so the steady-state cost is a dict hit."""

    def __init__(self, mesh: Mesh | None, batch_spec: PartitionSpec | None):
        self.mesh = mesh
        self.batch_spec = (batch_spec if batch_spec is not None
                           else default_batch_spec(mesh))
        self._cache: dict = {}

    def signature(self, vals):
        return tuple((tuple(v.shape), str(v.dtype)) for v in vals)

    def shardings(self, vals) -> tuple:
        """One NamedSharding per (flat) input value; None mesh -> Nones."""
        if self.mesh is None:
            return (None,) * len(vals)
        key = self.signature(vals)
        hit = self._cache.get(key)
        if hit is None:
            hit = tuple(
                NamedSharding(self.mesh,
                              trim_batch_spec(self.batch_spec, v.shape,
                                              self.mesh))
                for v in vals)
            self._cache[key] = hit
        return hit

    def place(self, vals, shardings=None):
        """Place each value with its trimmed sharding, SKIPPING the transfer
        when the array is already committed to a matching sharding (the
        pre-placed fast path a DeviceFeeder batch takes). Values that do
        move go host->device DIRECTLY (numpy straight into the sharded
        buffer, no intermediate default-device copy) and in ONE batched
        device_put dispatch. Returns (placed_tuple, n_transferred)."""
        vals = tuple(v._value if isinstance(v, Tensor) else v for v in vals)
        vals = tuple(v if hasattr(v, "shape") and hasattr(v, "dtype")
                     else jnp.asarray(v) for v in vals)
        if shardings is None:
            shardings = self.shardings(vals)
        placed = list(vals)
        move = []
        for i, (v, sh) in enumerate(zip(vals, shardings)):
            if sh is None:
                if not isinstance(v, jax.Array):
                    placed[i] = jnp.asarray(v)
                continue
            if (isinstance(v, jax.Array)
                    and getattr(v, "committed", False)
                    and v.sharding == sh):
                continue  # already resident with the right layout
            move.append(i)
        if move:
            out = jax.device_put([vals[i] for i in move],
                                 [shardings[i] for i in move])
            for i, v in zip(move, out):
                placed[i] = v
        return tuple(placed), len(move)


class LossFuture:
    """Deferred device->host read of a step's loss. The jax array inside may
    still be computing; `float(f)` / `f.value()` blocks until the producing
    step finishes (and therefore every earlier step in program order)."""

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value._value if isinstance(value, Tensor) else value

    def ready(self) -> bool:
        try:
            return self._value.is_ready()
        except AttributeError:  # backends without is_ready: treat as ready
            return True

    def value(self) -> float:
        return float(self._value)

    def block(self):
        jax.block_until_ready(self._value)
        return self

    def __float__(self):
        return self.value()

    def __repr__(self):
        if self.ready():
            return f"LossFuture({float(self._value):.6g})"
        return "LossFuture(<pending>)"


class DispatchWindow:
    """Bound the number of un-fetched steps in flight. `admit(loss)` enqueues
    the new step's loss and, once more than `window` steps are pending,
    blocks on the OLDEST one — program order then guarantees at most
    `window` compiled steps (and their input batches) are queued on the
    device, so run-ahead cannot OOM HBM no matter how rarely the caller
    reads metrics."""

    def __init__(self, window: int | None = None):
        if window is None:
            from paddle_tpu.core.flags import flag

            window = int(flag("async_dispatch_window"))
        self.window = max(int(window), 1)
        self._pending: collections.deque = collections.deque()

    def admit(self, loss):
        loss = loss._value if isinstance(loss, Tensor) else loss
        self._pending.append(loss)
        while len(self._pending) > self.window:
            jax.block_until_ready(self._pending.popleft())

    def drain(self):
        while self._pending:
            jax.block_until_ready(self._pending.popleft())

    def __len__(self):
        return len(self._pending)


class FeederWorkerError(RuntimeError):
    """A DeviceFeeder worker crash, re-raised in the CONSUMER with the
    position attached: `batch_index` is the 0-based index (within this
    feeder's stream) of the batch being processed when the worker died, and
    `phase` says whether fetch/collate ('collate') or the sharded
    host->device placement ('device_put') failed — so a supervisor can
    rebuild the pipeline at the right cursor and an operator knows whether
    to suspect the dataset or the device. The original exception rides as
    ``__cause__``."""

    def __init__(self, phase: str, batch_index: int, cause: BaseException):
        super().__init__(
            f"DeviceFeeder worker crashed in {phase!r} of batch "
            f"{batch_index}: {cause!r}")
        self.phase = phase
        self.batch_index = batch_index


class _End:
    __slots__ = ()


class DeviceFeeder:
    """Run an iterator's fetch+collate+sharded-placement on a background
    thread, keeping up to `depth` fully-placed batches in flight.

    The consumer iterates placed batches (same tuple/list/dict structure,
    leaves are committed jax Arrays); `CompiledTrainStep` recognizes the
    matching shardings and skips its own `device_put`. Worker exceptions are
    re-raised in the consumer at the position they occurred; `close()` (also
    called on exhaustion and by the context manager) stops the worker,
    unblocks it, and JOINS the thread — no leaked prefetchers."""

    def __init__(self, iterator: Iterable, mesh: Mesh | None = None,
                 batch_spec: PartitionSpec | None = None,
                 depth: int | None = None):
        if depth is None:
            from paddle_tpu.core.flags import flag

            depth = int(flag("prefetch_to_device_depth")) or 2
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = depth
        self.spec_cache = BatchSpecCache(mesh, batch_spec)
        self.batches_placed = 0  # diagnostics
        self.leaves_transferred = 0
        # the data CURSOR an elastic checkpoint records: batches the
        # CONSUMER took (prefetched-but-unconsumed batches must be replayed
        # after a resume, so `batches_placed` would over-count)
        self.batches_consumed = 0
        self._it = iter(iterator)
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._err: BaseException | None = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"{THREAD_PREFIX}.DeviceFeeder")
        self._thread.start()

    # -- worker --------------------------------------------------------------
    def _place_batch(self, batch):
        flat = []
        _tree_map(batch, lambda v: (flat.append(v), v)[1])
        placed, moved = self.spec_cache.place(flat)
        self.leaves_transferred += moved
        self.batches_placed += 1
        it = iter(placed)
        return _tree_map(batch, lambda _v: next(it))

    def _put(self, item) -> bool:
        return interruptible_put(self._q, item, self._stop)

    def _run(self):
        phase = "collate"
        try:
            while not self._stop.is_set():
                phase = "collate"
                with RecordEvent("DeviceFeeder::fetch"):
                    try:
                        faults.point("feeder.collate")
                        batch = next(self._it)
                    except StopIteration:
                        break
                phase = "device_put"
                with RecordEvent("DeviceFeeder::place"):
                    faults.point("feeder.device_put")
                    placed = self._place_batch(batch)
                if not self._put(placed):
                    return
        except BaseException as e:  # propagate to the consumer, with the
            # cursor + phase attached (batches_placed = the index of the
            # batch that was being processed when the worker died)
            err = FeederWorkerError(phase, self.batches_placed, e)
            err.__cause__ = e
            self._err = err
        finally:
            self._put(_End)

    # -- consumer ------------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item = self._q.get()
        if item is _End:
            err = self._err
            # close() also DRAINS the bounded queue: prefetched device
            # batches queued behind the crash are freed (HBM back) and a
            # producer blocked on a full queue can never deadlock shutdown
            self.close()
            if err is not None:
                self._err = None
                raise err
            raise StopIteration
        self.batches_consumed += 1
        return item

    def close(self):
        """Stop the worker and join its thread (idempotent)."""
        stop_and_join(self._q, self._stop, self._thread)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def prefetch_to_device(iterator: Iterable, mesh: Mesh | None = None,
                       batch_spec: PartitionSpec | None = None,
                       depth: int = 2) -> DeviceFeeder:
    """tf.data-style prefetch-to-device: wrap `iterator` in a DeviceFeeder
    that keeps `depth` sharded, device-resident batches ready ahead of the
    training loop. Use as a context manager (or fully exhaust it) so the
    worker thread is joined."""
    return DeviceFeeder(iterator, mesh=mesh, batch_spec=batch_spec,
                        depth=depth)
