"""Sequence packing: fuse variable-length documents into fixed [B, S] rows.

Reference analog: the T5/MaxText pack_dataset idiom. Real pretraining
corpora have skewed document lengths, so padded batches burn 30-60% of
attention/MLP FLOPs on pad tokens; packing makes every token in the batch a
real, loss-bearing token. The packed format is consumed end-to-end:

  * `segment_ids` drive the segment-aware flash kernel
    (paddle_tpu.ops.pallas.flash_attention) / the equivalent XLA mask in
    `F.scaled_dot_product_attention` — attention is block-diagonal per
    document, and whole K blocks are skipped when no segment overlaps;
  * `position_ids` restart at 0 per document so RoPE sees within-document
    positions, not row offsets;
  * `labels` are the within-document next-token targets, with the LAST token
    of every document (and all padding) set to `ignore_index` so no document
    predicts its neighbor's first token.

Format invariants the tests pin down:

  * per row, documents occupy a contiguous prefix in arrival order and
    padding (if any) is a contiguous tail;
  * `segment_ids` are NON-DECREASING along the row (documents numbered
    1..n in placement order, padding = n+1) — this keeps the kernel's
    per-block min/max segment ranges tight, i.e. maximal block skipping;
  * every input token of every document appears exactly once across the
    emitted batches (first-fit never drops or duplicates).

The packer is a plain streaming generator: wrap it in
`paddle_tpu.io.prefetch_to_device` and the packing work runs on the
DeviceFeeder's background thread, off the training loop's critical path.
`segment_ids`/`position_ids` are [B, S] integer leaves exactly like
`input_ids`, so `BatchSpecCache` shards them identically (batch dim over
dp/sharding, sequence dim over 'sep') with no extra configuration.
"""
from __future__ import annotations

from typing import Iterable, Iterator, Sequence

import numpy as np

__all__ = ["SequencePacker", "pack_examples", "pad_examples",
           "packing_stats", "unpack_batch"]

IGNORE_INDEX = -100  # the fused-CE / F.cross_entropy ignore_index default


def _as_tokens(example) -> np.ndarray:
    toks = np.asarray(example)
    if toks.ndim != 1:
        raise ValueError(
            f"each example must be a 1-D token sequence, got shape "
            f"{toks.shape}")
    return toks


class _Row:
    __slots__ = ("docs", "used")

    def __init__(self):
        self.docs: list[np.ndarray] = []
        self.used = 0

    def fits(self, n: int, seq_len: int) -> bool:
        return self.used + n <= seq_len

    def add(self, toks: np.ndarray):
        self.docs.append(toks)
        self.used += len(toks)


class SequencePacker:
    """Streaming first-fit packer producing `(input_ids, labels,
    segment_ids, position_ids)` batches of fixed shape [batch_size, seq_len].

    feed(example) -> list of zero or more completed batches;
    flush() -> the final partial batch (incomplete rows padded, missing rows
    all-padding) or None.

    Documents longer than seq_len are split into seq_len-sized chunks, each
    chunk its own segment (the chunk boundary token's label is ignored, like
    a document boundary). A batch is emitted as soon as an arriving document
    fits in NO open row and all batch_size rows are open — first-fit keeps
    rows open until then, so short documents backfill earlier rows' gaps.
    """

    def __init__(self, seq_len: int, batch_size: int, pad_id: int = 0,
                 ignore_index: int = IGNORE_INDEX, dtype=np.int32):
        if seq_len < 2:
            raise ValueError(f"seq_len must be >= 2, got {seq_len}")
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.seq_len = int(seq_len)
        self.batch_size = int(batch_size)
        self.pad_id = pad_id
        self.ignore_index = ignore_index
        self.dtype = dtype
        self._rows: list[_Row] = []
        # diagnostics (cumulative over the stream)
        self.docs_packed = 0
        self.tokens_packed = 0
        self.batches_emitted = 0
        self.pad_tokens_emitted = 0

    # -- packing --------------------------------------------------------------
    def feed(self, example) -> list[dict]:
        """Pack one document; returns the batches completed by it (0+)."""
        toks = _as_tokens(example)
        out = []
        if len(toks) == 0:
            return out
        for start in range(0, len(toks), self.seq_len):
            chunk = toks[start:start + self.seq_len]
            row = next((r for r in self._rows
                        if r.fits(len(chunk), self.seq_len)), None)
            if row is None:
                if len(self._rows) >= self.batch_size:
                    out.append(self._emit())
                row = _Row()
                self._rows.append(row)
            row.add(chunk)
            self.docs_packed += 1
            self.tokens_packed += len(chunk)
        return out

    def flush(self) -> dict | None:
        """Emit the final partial batch (None when nothing is buffered)."""
        if not self._rows:
            return None
        return self._emit()

    def _emit(self) -> dict:
        B, S = self.batch_size, self.seq_len
        ids = np.full((B, S), self.pad_id, self.dtype)
        labels = np.full((B, S), self.ignore_index, self.dtype)
        seg = np.zeros((B, S), self.dtype)
        pos = np.zeros((B, S), self.dtype)
        for r, row in enumerate(self._rows):
            off = 0
            for d, toks in enumerate(row.docs):
                n = len(toks)
                ids[r, off:off + n] = toks
                # within-document next-token labels; the boundary token
                # predicts nothing (ignore_index)
                labels[r, off:off + n - 1] = toks[1:]
                seg[r, off:off + n] = d + 1
                pos[r, off:off + n] = np.arange(n)
                off += n
            # the padded tail is its own (loss-free) trailing segment, so
            # segment ids stay non-decreasing along the row
            if off < S:
                seg[r, off:] = len(row.docs) + 1
                pos[r, off:] = np.arange(S - off)
                self.pad_tokens_emitted += S - off
        # rows that never opened are all-padding (segment 1, no loss)
        for r in range(len(self._rows), B):
            seg[r] = 1
            pos[r] = np.arange(S)
            self.pad_tokens_emitted += S
        self._rows = []
        self.batches_emitted += 1
        return {"input_ids": ids, "labels": labels,
                "segment_ids": seg, "position_ids": pos}


def pack_examples(examples: Iterable, seq_len: int, batch_size: int,
                  pad_id: int = 0, ignore_index: int = IGNORE_INDEX,
                  flush_remainder: bool = True,
                  packer: SequencePacker | None = None) -> Iterator[dict]:
    """Generator: stream documents through a first-fit `SequencePacker`,
    yielding packed [batch_size, seq_len] batches. Wrap the result in
    `prefetch_to_device` to run the packing on the feeder thread."""
    p = packer or SequencePacker(seq_len, batch_size, pad_id=pad_id,
                                 ignore_index=ignore_index)
    for ex in examples:
        yield from p.feed(ex)
    if flush_remainder:
        tail = p.flush()
        if tail is not None:
            yield tail


def pad_examples(examples: Iterable, seq_len: int, batch_size: int,
                 pad_id: int = 0,
                 ignore_index: int = IGNORE_INDEX) -> Iterator[dict]:
    """The PADDED baseline with the same schema: one document per row,
    truncated to seq_len. Same labels/positions semantics as the packer, so
    packed-vs-padded comparisons (bench `packing` arm, the equivalence
    test) differ ONLY in row layout."""
    rows: list[dict] = []

    def one_row(toks):
        # a batch_size-1 packer fed one document IS the padded row: same
        # label/segment/position semantics as the packed layout, no fusing
        p = SequencePacker(seq_len, 1, pad_id=pad_id,
                           ignore_index=ignore_index)
        p.feed(toks)
        row = p.flush()
        if row is None:  # no document: the packer's all-pad filler row
            row = {"input_ids": np.full((1, seq_len), pad_id, np.int32),
                   "labels": np.full((1, seq_len), ignore_index, np.int32),
                   "segment_ids": np.ones((1, seq_len), np.int32),
                   "position_ids": np.arange(seq_len, dtype=np.int32)[None]}
        return row

    def emit(rows):
        empty = one_row(np.zeros(0, np.int32))
        rows = rows + [empty] * (batch_size - len(rows))
        return {k: np.concatenate([r[k] for r in rows]) for k in rows[0]}

    for ex in examples:
        toks = _as_tokens(ex)[:seq_len]
        if len(toks) == 0:
            continue
        rows.append(one_row(toks))
        if len(rows) == batch_size:
            yield emit(rows)
            rows = []
    if rows:
        yield emit(rows)


def unpack_batch(batch: dict, pad_id: int = 0,
                 ignore_index: int = IGNORE_INDEX) -> list[np.ndarray]:
    """Recover the per-document token sequences from a packed batch (the
    round-trip check): split each row on segment-id changes and drop the
    trailing pad segment (all-`pad_id` ids with all-ignored labels at the row
    suffix; exact unless a real document IS a single pad_id token placed at a
    row end). Returns documents in row-major placement order."""
    ids = np.asarray(batch["input_ids"])
    seg = np.asarray(batch["segment_ids"])
    labels = np.asarray(batch["labels"])
    docs = []
    for r in range(ids.shape[0]):
        bounds = [0] + (1 + np.flatnonzero(np.diff(seg[r]))).tolist() + [
            ids.shape[1]]
        for a, b in zip(bounds[:-1], bounds[1:]):
            if (b == ids.shape[1] and (ids[r, a:b] == pad_id).all()
                    and (labels[r, a:b] == ignore_index).all()):
                continue  # the padded tail
            docs.append(ids[r, a:b])
    return docs


def packing_stats(lengths: Sequence[int], seq_len: int,
                  batch_size: int) -> dict:
    """What padding costs for a corpus of document `lengths`: the padded
    baseline's pad fraction, and the rows/batches the packed layout needs.
    Purely combinatorial, but replays the REAL policies: the packed side
    feeds full lengths through a `SequencePacker` (documents longer than
    seq_len chunk, exactly as `pack_examples` does), the padded side
    truncates to seq_len (exactly as `pad_examples` does) — so the two
    token totals can differ on corpora with overlong documents."""
    lengths = [int(n) for n in lengths if int(n) > 0]
    capped = [min(n, seq_len) for n in lengths]
    padded_tokens_real = sum(capped)  # pad_examples truncates overflow
    padded_rows = len(lengths)
    padded_tokens = padded_rows * seq_len
    total = sum(lengths)  # the packer keeps every token (chunking)
    p = SequencePacker(seq_len, batch_size)
    batches = sum(len(p.feed(np.zeros(n, np.int32))) for n in lengths)
    if p._rows:
        packed_rows = batches * batch_size + len(p._rows)
        batches += 1
    else:
        packed_rows = batches * batch_size
    # *_emitted: what pack_examples actually ships — final partial batches
    # are padded to full [batch_size, seq_len] shape with all-pad filler
    # rows, which the training step really computes
    rows_emitted = batches * batch_size
    return {
        "documents": len(lengths),
        "real_tokens": total,
        "real_tokens_padded": padded_tokens_real,
        "padded_rows": padded_rows,
        "padding_frac_padded": 1.0 - padded_tokens_real / max(padded_tokens, 1),
        "packed_rows": packed_rows,
        "packed_batches": batches,
        "packed_rows_emitted": rows_emitted,
        "padding_frac_packed": 1.0 - total / max(packed_rows * seq_len, 1),
        "padding_frac_packed_emitted":
            1.0 - total / max(rows_emitted * seq_len, 1),
        "row_compression": padded_rows / max(packed_rows, 1),
    }
