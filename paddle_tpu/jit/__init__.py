from paddle_tpu.jit.api import InputSpec, not_to_static, save, load, to_static  # noqa: F401
