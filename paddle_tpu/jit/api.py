"""Graph capture: `to_static` whole-program XLA compilation.

Reference parity: paddle.jit.to_static (python/paddle/jit/api.py:173) which
captures Python into a static Program via SOT bytecode translation
(jit/sot/translate.py:32) and runs it on PirInterpreter. TPU-native design:
capture-by-trace into ONE compiled XLA program — `jax.jit` over a purely
functional form of the layer/function. The eager tape is bypassed inside the
capture; gradients of a captured function flow through `jax.vjp` of the whole
program, so backward is whole-graph compiled too (the analog of the reference's
static backward pass construction, ir_backward.py).

No bytecode translator is needed: our eager ops are pure jax functions of
`Tensor._value`, so ordinary Python execution under jax tracers IS the capture.
Data-dependent Python control flow must use paddle_tpu.jit.cond/while_loop
(-> lax.cond / lax.while_loop), mirroring how SOT falls back on control-flow ops.
"""
from __future__ import annotations

import functools
import os
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.autograd import tape as _tape
from paddle_tpu.core.dtype import to_jax_dtype
from paddle_tpu.core.tensor import Tensor, apply_op

__all__ = ["InputSpec", "to_static", "not_to_static", "save", "load", "cond", "while_loop", "scan"]


class InputSpec:
    """Shape/dtype declaration (reference: paddle.static.InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def _unwrap_tree(x):
    return jax.tree_util.tree_map(
        lambda v: v._value if isinstance(v, Tensor) else v, x,
        is_leaf=lambda v: isinstance(v, Tensor),
    )


class StaticFunction:
    """A captured callable: params are implicit inputs, the body is one XLA program."""

    def __init__(self, fn: Callable, layer=None, input_spec=None, backend=None):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        functools.update_wrapper(self, fn, updated=())
        self._params: list[Tensor] | None = None
        self._jitted = None

    # -- functionalization --------------------------------------------------
    def _collect_params(self):
        if self._layer is not None:
            return list(self._layer.parameters())
        return []

    def _pure(self, param_vals: Sequence, args_vals: tuple, kwargs_vals: dict):
        """Run fn with params + inputs bound to (possibly traced) buffers."""
        params = self._params
        old = [p._value for p in params]
        try:
            for p, v in zip(params, param_vals):
                p._set_value(v)
            t_args = jax.tree_util.tree_map(lambda v: Tensor(v) if _is_arr(v) else v, args_vals)
            t_kwargs = jax.tree_util.tree_map(lambda v: Tensor(v) if _is_arr(v) else v, kwargs_vals)
            with _tape.no_grad():
                out = self._fn(*t_args, **t_kwargs)
            return _unwrap_tree(out)
        finally:
            for p, v in zip(params, old):
                p._set_value(v)

    def __call__(self, *args, **kwargs):
        if self._params is None:
            self._params = self._collect_params()
        params = self._params
        args_vals = _unwrap_tree(args)
        kwargs_vals = _unwrap_tree(kwargs)

        needs_grad = _tape.grad_enabled() and any(not p.stop_gradient for p in params)
        in_grad = _tape.grad_enabled() and any(
            isinstance(t, Tensor) and not t.stop_gradient
            for t in jax.tree_util.tree_leaves(args, is_leaf=lambda v: isinstance(v, Tensor))
        )

        if needs_grad or in_grad:
            # whole-program forward + whole-program vjp through the tape
            flat_p = [p._value for p in params]

            def f(*pv):
                return self._pure(pv, args_vals, kwargs_vals)

            out = apply_op(f, *params, name=f"to_static:{self._fn.__name__}")
            return _rewrap(out)

        if self._jitted is None:
            self._jitted = jax.jit(
                lambda pv, av, kv: self._pure(pv, av, kv),
            )
        out_vals = self._jitted([p._value for p in params], args_vals, kwargs_vals)
        return jax.tree_util.tree_map(lambda v: Tensor(v) if _is_arr(v) else v, out_vals)

    @property
    def code(self):
        import inspect

        try:
            return inspect.getsource(self._fn)
        except OSError:
            return "<source unavailable>"

    def concrete_program(self):
        return self._jitted


def _is_arr(v):
    return isinstance(v, (jax.Array, np.ndarray)) or hasattr(v, "shape") and hasattr(v, "dtype")


def _rewrap(out):
    # apply_op returns Tensor or tuple of Tensors for tuple outputs
    return out


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """Decorator/wrapper: compile a function or Layer.forward to one XLA program."""

    def wrap(fn):
        from paddle_tpu.nn.layer.layers import Layer

        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, layer=fn, input_spec=input_spec, backend=backend)
            fn.forward = sf
            return fn
        return StaticFunction(fn, layer=None, input_spec=input_spec, backend=backend)

    if function is not None:
        return wrap(function)
    return wrap


def not_to_static(fn):
    fn._not_to_static = True
    return fn


# ---- compiler-friendly control flow (lax wrappers) ------------------------

def cond(pred, true_fn, false_fn, *operands):
    """paddle.static.nn.cond analog -> lax.cond (traceable branch select)."""
    p = pred._value if isinstance(pred, Tensor) else pred
    vals = _unwrap_tree(operands)

    def tf(ops):
        return _unwrap_tree(true_fn(*jax.tree_util.tree_map(Tensor, ops)))

    def ff(ops):
        return _unwrap_tree(false_fn(*jax.tree_util.tree_map(Tensor, ops)))

    out = jax.lax.cond(p, tf, ff, vals)
    return jax.tree_util.tree_map(Tensor, out)


def while_loop(cond_fn, body_fn, loop_vars):
    vals = _unwrap_tree(loop_vars)

    def c(v):
        r = cond_fn(*jax.tree_util.tree_map(Tensor, v))
        return r._value if isinstance(r, Tensor) else r

    def b(v):
        return _unwrap_tree(body_fn(*jax.tree_util.tree_map(Tensor, v)))

    out = jax.lax.while_loop(c, b, vals)
    return jax.tree_util.tree_map(Tensor, out)


def scan(body_fn, init, xs):
    init_v = _unwrap_tree(init)
    xs_v = _unwrap_tree(xs)

    def b(carry, x):
        c, y = body_fn(jax.tree_util.tree_map(Tensor, carry), jax.tree_util.tree_map(Tensor, x))
        return _unwrap_tree(c), _unwrap_tree(y)

    carry, ys = jax.lax.scan(b, init_v, xs_v)
    return jax.tree_util.tree_map(Tensor, carry), jax.tree_util.tree_map(Tensor, ys)


# ---- save / load (deployment artifacts) -----------------------------------

def save(layer, path, input_spec=None, **configs):
    """Serialize a layer: params + config. (Reference: paddle.jit.save producing
    inference programs; here the artifact is params + a module path, since XLA
    recompiles the program from code at load time.)"""
    from paddle_tpu.framework.io_ import save as _save

    state = layer.state_dict() if hasattr(layer, "state_dict") else layer
    _save({"state_dict": state, "class": type(layer).__module__ + "." + type(layer).__name__},
          path + ".pdparams")


def load(path, **configs):
    from paddle_tpu.framework.io_ import load as _load

    return _load(path + ".pdparams")
