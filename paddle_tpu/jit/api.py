"""Graph capture: `to_static` whole-program XLA compilation.

Reference parity: paddle.jit.to_static (python/paddle/jit/api.py:173) which
captures Python into a static Program via SOT bytecode translation
(jit/sot/translate.py:32) and runs it on PirInterpreter. TPU-native design:
capture-by-trace into ONE compiled XLA program — `jax.jit` over a purely
functional form of the layer/function. The eager tape is bypassed inside the
capture; gradients of a captured function flow through `jax.vjp` of the whole
program, so backward is whole-graph compiled too (the analog of the reference's
static backward pass construction, ir_backward.py).

No bytecode translator is needed: our eager ops are pure jax functions of
`Tensor._value`, so ordinary Python execution under jax tracers IS the capture.
Data-dependent Python control flow must use paddle_tpu.jit.cond/while_loop
(-> lax.cond / lax.while_loop), mirroring how SOT falls back on control-flow ops.
"""
from __future__ import annotations

import functools
import os
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.autograd import tape as _tape
from paddle_tpu.core.dtype import to_jax_dtype
from paddle_tpu.core.tensor import Tensor, apply_op

__all__ = ["InputSpec", "to_static", "not_to_static", "save", "load", "cond", "while_loop", "scan"]


class InputSpec:
    """Shape/dtype declaration (reference: paddle.static.InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def _unwrap_tree(x):
    return jax.tree_util.tree_map(
        lambda v: v._value if isinstance(v, Tensor) else v, x,
        is_leaf=lambda v: isinstance(v, Tensor),
    )


class StaticFunction:
    """A captured callable: params are implicit inputs, the body is one XLA program."""

    def __init__(self, fn: Callable, layer=None, input_spec=None, backend=None,
                 bucketize=False):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._bucketize = bucketize
        functools.update_wrapper(self, fn, updated=())
        self._params: list[Tensor] | None = None
        self._jitted = None
        self._warmed = False
        self.trace_count = 0  # diagnostics: how many programs were traced

    # -- functionalization --------------------------------------------------
    def _collect_params(self):
        if self._layer is not None:
            return list(self._layer.parameters())
        return []

    def _pure(self, param_vals: Sequence, args_vals: tuple, kwargs_vals: dict):
        """Run fn with params + inputs bound to (possibly traced) buffers."""
        self.trace_count += 1
        params = self._params
        old = [p._value for p in params]
        try:
            for p, v in zip(params, param_vals):
                p._set_value(v)
            t_args = jax.tree_util.tree_map(lambda v: Tensor(v) if _is_arr(v) else v, args_vals)
            t_kwargs = jax.tree_util.tree_map(lambda v: Tensor(v) if _is_arr(v) else v, kwargs_vals)
            with _tape.no_grad():
                out = self._fn(*t_args, **t_kwargs)
            return _unwrap_tree(out)
        finally:
            for p, v in zip(params, old):
                p._set_value(v)

    @staticmethod
    def _bucket(n: int) -> int:
        """Next power-of-two batch bucket (min 1) — SURVEY §7.3 hard part 5:
        varying batch sizes hit a handful of compiled programs, not one per
        distinct size."""
        b = 1
        while b < n:
            b *= 2
        return b

    def _pad_to_buckets(self, args_vals):
        """Pad dim 0 of each array input up to its bucket; return (padded,
        (original_n, bucket)) or (args, None) when already bucket-sized."""
        ns = [v.shape[0] for v in jax.tree_util.tree_leaves(args_vals)
              if _is_arr(v) and getattr(v, "ndim", 0) >= 1]
        if not ns or len(set(ns)) != 1:
            return args_vals, None  # no shared batch dim: skip bucketing
        n = ns[0]
        b = self._bucket(n)
        if b == n:
            return args_vals, None

        def pad(v):
            if _is_arr(v) and getattr(v, "ndim", 0) >= 1 and v.shape[0] == n:
                widths = [(0, b - n)] + [(0, 0)] * (v.ndim - 1)
                return jnp.pad(jnp.asarray(v), widths)
            return v

        return jax.tree_util.tree_map(pad, args_vals), (n, b)

    @staticmethod
    def _slice_outputs(out_vals, n, b):
        """Cut padded rows back out. Only leaves whose dim 0 equals the
        padded bucket are sliced; a 0-d (reduced) output cannot be un-padded
        and means the function mixed phantom rows into a reduction — raise
        rather than return silently-wrong numbers."""

        def cut(v):
            if not _is_arr(v):
                return v
            if getattr(v, "ndim", 0) == 0:
                raise ValueError(
                    "bucketize=True requires per-row outputs: a scalar "
                    "(batch-reduced) output would include the padded rows. "
                    "Reduce outside the to_static function or disable "
                    "bucketize.")
            return v[:n] if v.shape[0] == b else v

        return jax.tree_util.tree_map(cut, out_vals)

    def __call__(self, *args, **kwargs):
        if self._params is None:
            self._params = self._collect_params()
        params = self._params
        args_vals = _unwrap_tree(args)
        kwargs_vals = _unwrap_tree(kwargs)

        needs_grad = _tape.grad_enabled() and any(not p.stop_gradient for p in params)
        in_grad = _tape.grad_enabled() and any(
            isinstance(t, Tensor) and not t.stop_gradient
            for t in jax.tree_util.tree_leaves(args, is_leaf=lambda v: isinstance(v, Tensor))
        )

        bucket_info = None
        if self._bucketize and not (needs_grad or in_grad):
            if kwargs_vals:
                import warnings

                warnings.warn("bucketize=True is skipped for keyword-argument "
                              "calls; pass batch inputs positionally")
            else:
                args_vals, bucket_info = self._pad_to_buckets(args_vals)

        from paddle_tpu.jit.dy2static import (Dy2StaticControlFlowError,
                                              convert_control_flow)

        for attempt in range(2):
            try:
                if needs_grad or in_grad:
                    # whole-program forward + whole-program vjp through the tape

                    def f(*pv):
                        return self._pure(pv, args_vals, kwargs_vals)

                    out = apply_op(f, *params,
                                   name=f"to_static:{self._fn.__name__}")
                    return _rewrap(out)

                if self._jitted is None:
                    self._jitted = jax.jit(
                        lambda pv, av, kv: self._pure(pv, av, kv),
                    )
                out_vals = self._jitted([p._value for p in params], args_vals,
                                        kwargs_vals)
                break
            except Dy2StaticControlFlowError:
                # data-dependent Python control flow hit the trace: try the
                # dy2static AST pass once (reference jit/dy2static/), else
                # surface the guided error
                if attempt == 1 or getattr(self._fn, "__dy2static_converted__",
                                           False):
                    raise
                target = self._fn
                bound_self = getattr(target, "__self__", None)
                conv = convert_control_flow(
                    target.__func__ if bound_self is not None else target)
                if conv is None:
                    raise
                if bound_self is not None:
                    # re-bind a converted forward to its layer
                    def _bound(*a, _c=conv, _s=bound_self, **k):
                        return _c(_s, *a, **k)

                    _bound.__dy2static_converted__ = True
                    conv = _bound
                self._fn = conv
                self._jitted = None
        if bucket_info is not None:
            out_vals = self._slice_outputs(out_vals, *bucket_info)
        return jax.tree_util.tree_map(lambda v: Tensor(v) if _is_arr(v) else v, out_vals)

    def warmup(self):
        """AOT-compile from the declared InputSpec shapes (reference: the
        static program is built at to_static time, not first call). Only
        fully-concrete specs warm up — compiling a stand-in batch size for a
        dynamic dim would never be reused."""
        if self._input_spec is None:
            return False
        if any(d is None or d == -1 for s in self._input_spec for d in s.shape):
            return False
        if self._params is None:
            self._params = self._collect_params()
        abstract = tuple(
            jax.ShapeDtypeStruct(tuple(int(d) for d in s.shape),
                                 to_jax_dtype(s.dtype))
            for s in self._input_spec)
        if self._jitted is None:
            self._jitted = jax.jit(lambda pv, av, kv: self._pure(pv, av, kv))
        p_abs = [jax.ShapeDtypeStruct(p._value.shape, p._value.dtype)
                 for p in self._params]
        self._jitted.lower(p_abs, abstract, {}).compile()
        self._warmed = True
        return True

    @property
    def code(self):
        import inspect

        try:
            return inspect.getsource(self._fn)
        except OSError:
            return "<source unavailable>"

    def concrete_program(self):
        return self._jitted


def _is_arr(v):
    return isinstance(v, (jax.Array, np.ndarray)) or hasattr(v, "shape") and hasattr(v, "dtype")


def _rewrap(out):
    # apply_op returns Tensor or tuple of Tensors for tuple outputs
    return out


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              bucketize=False, **kwargs):
    """Decorator/wrapper: compile a function or Layer.forward to one XLA
    program. bucketize=True pads a shared leading batch dim up to power-of-two
    buckets (outputs sliced back), bounding recompiles under varying batch
    sizes (SURVEY §7.3 shape bucketing; the reference predictor's dynamic-
    shape strategy)."""

    def wrap(fn):
        from paddle_tpu.nn.layer.layers import Layer

        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, layer=fn, input_spec=input_spec,
                                backend=backend, bucketize=bucketize)
            fn.forward = sf
            if input_spec is not None:
                try:
                    sf.warmup()
                except Exception:
                    pass  # warmup is an optimization; first call still compiles
            return fn
        return StaticFunction(fn, layer=None, input_spec=input_spec,
                              backend=backend, bucketize=bucketize)

    if function is not None:
        return wrap(function)
    return wrap


def not_to_static(fn):
    fn._not_to_static = True
    return fn


# ---- compiler-friendly control flow (lax wrappers) ------------------------

def cond(pred, true_fn, false_fn, *operands):
    """paddle.static.nn.cond analog -> lax.cond (traceable branch select)."""
    p = pred._value if isinstance(pred, Tensor) else pred
    vals = _unwrap_tree(operands)

    def tf(ops):
        return _unwrap_tree(true_fn(*jax.tree_util.tree_map(Tensor, ops)))

    def ff(ops):
        return _unwrap_tree(false_fn(*jax.tree_util.tree_map(Tensor, ops)))

    out = jax.lax.cond(p, tf, ff, vals)
    return jax.tree_util.tree_map(Tensor, out)


def while_loop(cond_fn, body_fn, loop_vars):
    vals = _unwrap_tree(loop_vars)

    def c(v):
        r = cond_fn(*jax.tree_util.tree_map(Tensor, v))
        return r._value if isinstance(r, Tensor) else r

    def b(v):
        return _unwrap_tree(body_fn(*jax.tree_util.tree_map(Tensor, v)))

    out = jax.lax.while_loop(c, b, vals)
    return jax.tree_util.tree_map(Tensor, out)


def scan(body_fn, init, xs):
    init_v = _unwrap_tree(init)
    xs_v = _unwrap_tree(xs)

    def b(carry, x):
        c, y = body_fn(jax.tree_util.tree_map(Tensor, carry), jax.tree_util.tree_map(Tensor, x))
        return _unwrap_tree(c), _unwrap_tree(y)

    carry, ys = jax.lax.scan(b, init_v, xs_v)
    return jax.tree_util.tree_map(Tensor, carry), jax.tree_util.tree_map(Tensor, ys)


# ---- save / load (deployment artifacts) -----------------------------------

def _export(jit_fn, p_abs, abstract):
    """jax.export across API generations, probing the signature instead of
    catching TypeError around the traced call (which would misattribute
    user-code errors and silently drop cross-platform lowering)."""
    import inspect

    from jax import export as jexport

    params = inspect.signature(jexport.export).parameters
    if "platforms" in params:
        return jexport.export(jit_fn, platforms=("cpu", "tpu"))(p_abs, abstract)
    if "lowering_platforms" in params:
        return jexport.export(jit_fn, lowering_platforms=("cpu", "tpu"))(p_abs, abstract)
    return jexport.export(jit_fn)(p_abs, abstract)


def save(layer, path, input_spec=None, quantize=None, **configs):
    """Serialize a layer into a RUNNABLE deployment artifact: the forward is
    captured and exported as serialized StableHLO (jax.export) together with
    the parameter values, so `jit.load` returns a callable that executes
    WITHOUT importing the model class — the TPU-native analog of the
    reference's saved inference program + TranslatedLayer
    (python/paddle/jit/api.py:173 save, translated_layer.py; served by
    AnalysisPredictor in C++).

    input_spec: list of InputSpec/Tensors/arrays declaring the forward's
    input shapes+dtypes. Required for export; without it only the legacy
    params artifact is written.

    quantize='wo_int8': weight-only int8 serving artifact. Every 2-D float
    matmul weight is stored as per-output-channel int8 codes + an fp32
    scale vector (paddle_tpu.quantization.quantize_weight_int8, the
    AbsmaxChannelWiseObserver absmax rule); the exported program takes the
    int8 params as inputs and dequantizes ON USE (``q.astype(f32) * scale``
    cast back to the weight's original dtype), so the artifact is ~half the
    bf16 bytes, loaders (`jit.load`, `inference.serve.Artifact`) need no
    changes, and activations/compute dtype are untouched.
    """
    from paddle_tpu.framework.io_ import save as _save

    if quantize not in (None, "", "wo_int8"):
        raise ValueError(
            f"unknown quantize scheme {quantize!r}; expected 'wo_int8'")

    state = layer.state_dict() if hasattr(layer, "state_dict") else layer
    cls = type(layer).__module__ + "." + type(layer).__name__
    _save({"state_dict": state, "class": cls}, path + ".pdparams")

    if input_spec is None:
        return

    params = list(layer.parameters()) if hasattr(layer, "parameters") else []
    param_vals = [np.asarray(p._value) for p in params]

    def bind(pv, xs):
        old = [p._value for p in params]
        try:
            for p, v in zip(params, pv):
                p._set_value(v)
            t_args = [Tensor(x) for x in xs]
            with _tape.no_grad():
                out = layer(*t_args)
            return _unwrap_tree(out)
        finally:
            for p, v in zip(params, old):
                p._set_value(v)

    q_meta = None
    if quantize == "wo_int8":
        from paddle_tpu.quantization import quantize_weight_int8

        q_idx, scales, stored = [], [], []
        for i, v in enumerate(param_vals):
            # 2-D float weights (matmul/embedding tables) quantize
            # per-output-channel; 1-D biases/norm gains (and tiny weights,
            # where the scale vector would not pay for itself) stay as-is.
            # jnp.issubdtype: bfloat16 is an ml_dtypes scalar numpy does not
            # classify as floating
            if (v.ndim == 2 and jnp.issubdtype(v.dtype, jnp.floating)
                    and v.size >= 1024):
                q, sc = quantize_weight_int8(v, quant_axis=-1)
                q_idx.append(i)
                scales.append(sc)
                stored.append(q)
            else:
                stored.append(v)
        q_dtypes = [str(param_vals[i].dtype) for i in q_idx]
        q_meta = {"scheme": "wo_int8", "indices": list(q_idx),
                  "orig_dtypes": q_dtypes}
        n_p = len(param_vals)

        def pure(pv, xs):
            # pv = [stored params..., scale vectors...]; dequant-on-use —
            # the int8 codes are program INPUTS, so the full-precision
            # weight exists only transiently inside each call
            full = list(pv[:n_p])
            for j, i in enumerate(q_idx):
                dq = full[i].astype(jnp.float32) * pv[n_p + j]
                full[i] = dq.astype(to_jax_dtype(q_dtypes[j]))
            return bind(full, xs)

        param_vals = stored + scales
    else:
        pure = bind

    def _abstracts(dynamic: bool):
        from jax import export as jexport

        out = []
        for si, s in enumerate(input_spec):
            if isinstance(s, InputSpec):
                dims = [None if (d is None or d == -1) else int(d) for d in s.shape]
                if dynamic and any(d is None for d in dims):
                    shape = jexport.symbolic_shape(
                        ",".join(f"b{si}_{i}" if d is None else str(d)
                                 for i, d in enumerate(dims)))
                else:
                    shape = tuple(1 if d is None else d for d in dims)
                out.append(jax.ShapeDtypeStruct(shape, to_jax_dtype(s.dtype)))
            else:
                v = s._value if isinstance(s, Tensor) else np.asarray(s)
                out.append(jax.ShapeDtypeStruct(v.shape, v.dtype))
        return out

    p_abs = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in param_vals]
    jit_pure = jax.jit(pure)
    try:  # dynamic dims export as jax symbolic shapes when the program allows
        abstract = _abstracts(dynamic=True)
        exported = _export(jit_pure, p_abs, abstract)
    except Exception:
        abstract = _abstracts(dynamic=False)
        exported = _export(jit_pure, p_abs, abstract)
    from paddle_tpu.inference.artifact import write_artifact

    blob = {
        "stablehlo": exported.serialize(),
        "params": param_vals,
        "class": cls,
        # symbolic dims stringified: JSON metadata, not jax _DimExpr objects
        "in_shapes": [(tuple(d if isinstance(d, int) else str(d) for d in a.shape),
                       str(a.dtype)) for a in abstract],
    }
    if q_meta is not None:
        blob["quantize"] = q_meta
    # data-only container (meta.json + stablehlo.bin + raw param members) —
    # the .pdmodel load path never unpickles (paddle_tpu.inference.artifact).
    # NOTE: the optional .pdparams state-dict sidecar above still uses the
    # framework pickle format; `load` below only reads it for state_dict()
    # metadata — treat .pdparams like code, or serve through
    # paddle_tpu.inference.serve, which never touches it.
    write_artifact(path + ".pdmodel", blob)


class TranslatedLayer:
    """A loaded deployment artifact: executes the exported StableHLO program
    with the saved parameters — no source class needed (reference
    jit/translated_layer.py TranslatedLayer)."""

    def __init__(self, blob):
        from jax import export as jexport

        self._exported = jexport.deserialize(bytearray(blob["stablehlo"]))
        self._params = [jnp.asarray(v) for v in blob["params"]]
        self._state = blob.get("state_dict")
        self.class_name = blob.get("class", "")
        self.in_shapes = blob.get("in_shapes", [])

    def __call__(self, *args):
        xs = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        out = self._exported.call(self._params, xs)
        return jax.tree_util.tree_map(
            lambda v: Tensor(v) if _is_arr(v) else v, out)

    forward = __call__

    def eval(self):
        return self

    def parameters(self):
        return [Tensor(v) for v in self._params]

    def state_dict(self):
        return self._state or {}


def load(path, **configs):
    """Load a jit.save artifact. Returns a runnable TranslatedLayer when the
    exported program exists; otherwise the legacy params dict."""
    from paddle_tpu.framework.io_ import load as _load
    from paddle_tpu.inference.artifact import read_artifact

    if os.path.exists(path + ".pdmodel"):
        blob = read_artifact(path + ".pdmodel")
        try:
            blob.setdefault("state_dict", _load(path + ".pdparams").get("state_dict"))
        except Exception:
            pass
        return TranslatedLayer(blob)
    return _load(path + ".pdparams")
