"""dy2static control-flow conversion (restricted AST pass) + guided errors.

Reference parity: paddle.jit's SOT bytecode capture (jit/sot/translate.py:32)
and the AST dy2static package (jit/dy2static/) convert data-dependent Python
control flow (`if tensor:`, `while tensor:`, `for i in range(tensor):`) into
graph ops. TPU-native design: capture-by-trace makes ordinary Python the
translator, so only DATA-DEPENDENT control flow needs help. Two pieces:

1. Detection: `Tensor.__bool__` under a jax trace raises
   `Dy2StaticControlFlowError` naming `paddle.jit.cond/while_loop` (instead
   of jax's tracer-leak message).
2. Conversion: `convert_control_flow(fn)` rewrites SIMPLE tensor-conditioned
   `if`/`while`/`for ... in range(...)` statements (straight-line bodies that
   only assign local names — no return/break/continue/yield) into
   `lax.cond` / `lax.while_loop` / `lax.fori_loop` calls.
   `StaticFunction.__call__` retries with the converted function when the
   first trace hits the detection error; unconvertible functions re-raise
   the guided message.
"""
from __future__ import annotations

import ast
import inspect
import textwrap

import jax
import jax.numpy as jnp

__all__ = ["Dy2StaticControlFlowError", "convert_control_flow"]

GUIDANCE = (
    "data-dependent Python control flow reached a traced Tensor "
    "(`if`/`while` on a tensor value, or bool() during jit/to_static "
    "capture). Rewrite with paddle_tpu.jit.cond / paddle_tpu.jit.while_loop "
    "/ paddle_tpu.jit.scan (compiled lax control flow), or keep the branch "
    "simple (straight-line assignments only) so to_static's dy2static AST "
    "pass can convert it automatically."
)


class Dy2StaticControlFlowError(TypeError):
    pass


# --------------------------------------------------------------------------
# runtime helpers injected into converted functions


def _v(x):
    from paddle_tpu.core.tensor import Tensor

    return x._value if isinstance(x, Tensor) else x


def _is_traced(x):
    return isinstance(_v(x), jax.core.Tracer)


def _wrap_out(vals):
    from paddle_tpu.core.tensor import Tensor

    return tuple(Tensor(v) if isinstance(v, (jax.Array, jnp.ndarray))
                 or isinstance(v, jax.core.Tracer) else v for v in vals)


def _unwrap_tuple(t):
    return tuple(jnp.asarray(_v(x)) for x in t)


def _pt_cvt_if(cond, true_fn, false_fn, env):
    if not _is_traced(cond):
        return true_fn(env) if bool(_v(cond)) else false_fn(env)

    def br(fn):
        def g(_):
            return _unwrap_tuple(fn(env))

        return g

    outs = jax.lax.cond(jnp.asarray(_v(cond)).astype(bool),
                        br(true_fn), br(false_fn), None)
    return _wrap_out(outs)


def _pt_cvt_while(cond_fn, body_fn, carry):
    from paddle_tpu.core.tensor import Tensor

    probe = cond_fn(tuple(carry))
    if not _is_traced(probe) and not any(_is_traced(c) for c in carry):
        carry = tuple(carry)
        while bool(_v(cond_fn(carry))):
            carry = tuple(body_fn(carry))
        return carry

    def c(cu):
        return jnp.asarray(_v(cond_fn(_wrap_out(cu)))).astype(bool)

    def b(cu):
        return _unwrap_tuple(body_fn(_wrap_out(cu)))

    outs = jax.lax.while_loop(c, b, _unwrap_tuple(carry))
    return _wrap_out(outs)


def _pt_cvt_for(n, body_fn, carry):
    if not _is_traced(n):
        carry = tuple(carry)
        for i in range(int(_v(n))):
            carry = tuple(body_fn(i, carry))
        return carry

    def b(i, cu):
        from paddle_tpu.core.tensor import Tensor

        return _unwrap_tuple(body_fn(Tensor(i), _wrap_out(cu)))

    outs = jax.lax.fori_loop(0, jnp.asarray(_v(n)).astype(jnp.int32),
                             b, _unwrap_tuple(carry))
    return _wrap_out(outs)


_HELPERS = {"__pt_cvt_if": _pt_cvt_if, "__pt_cvt_while": _pt_cvt_while,
            "__pt_cvt_for": _pt_cvt_for}


# --------------------------------------------------------------------------
# the AST pass


def _collect_assigned(stmts) -> set:
    names = set()

    def tgt(t):
        if isinstance(t, ast.Name):
            names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                tgt(e)

    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    tgt(t)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                tgt(node.target)
    return names


def _straight_line(stmts) -> bool:
    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, (ast.Return, ast.Break, ast.Continue,
                                 ast.Yield, ast.YieldFrom, ast.Raise,
                                 ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.Global, ast.Nonlocal)):
                return False
    return True


def _names_tuple(names, ctx):
    return ast.Tuple([ast.Name(n, ctx()) for n in names], ctx())


def _fndef(name, argnames, body):
    args = ast.arguments(posonlyargs=[], args=[ast.arg(a) for a in argnames],
                         vararg=None, kwonlyargs=[], kw_defaults=[],
                         kwarg=None, defaults=[])
    return ast.FunctionDef(name=name, args=args, body=body,
                           decorator_list=[], returns=None, type_params=[])


class _Transformer(ast.NodeTransformer):
    def __init__(self):
        self.n = 0
        self.converted = 0

    def _unpack(self, names, src_name):
        return ast.Assign(
            targets=[_names_tuple(names, ast.Store)],
            value=ast.Name(src_name, ast.Load()))

    def visit_If(self, node):
        self.generic_visit(node)
        if not (_straight_line(node.body) and _straight_line(node.orelse)):
            return node
        names = sorted(_collect_assigned(node.body)
                       | _collect_assigned(node.orelse))
        if not names:
            return node
        i = self.n
        self.n += 1
        self.converted += 1
        # branch defs take the enclosing locals() so names read-then-assigned
        # inside a branch see their current outer values
        prelude = [ast.Assign(
            targets=[ast.Name(n, ast.Store())],
            value=ast.Call(
                ast.Attribute(ast.Name("__pt_env", ast.Load()), "get",
                              ast.Load()),
                [ast.Constant(n)], [])) for n in names]
        ret = ast.Return(_names_tuple(names, ast.Load))
        tdef = _fndef(f"__pt_true_{i}", ["__pt_env"],
                      prelude + list(node.body) + [ret])
        fdef = _fndef(f"__pt_false_{i}", ["__pt_env"],
                      prelude + (list(node.orelse) or [ast.Pass()]) + [ret])
        assign = ast.Assign(
            targets=[_names_tuple(names, ast.Store)],
            value=ast.Call(ast.Name("__pt_cvt_if", ast.Load()),
                           [node.test,
                            ast.Name(f"__pt_true_{i}", ast.Load()),
                            ast.Name(f"__pt_false_{i}", ast.Load()),
                            ast.Call(ast.Name("locals", ast.Load()), [], [])],
                           []))
        return [tdef, fdef, assign]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or not _straight_line(node.body):
            return node
        names = sorted(_collect_assigned(node.body))
        if not names:
            return node
        i = self.n
        self.n += 1
        self.converted += 1
        unpack = self._unpack(names, "__pt_c")
        cdef = _fndef(f"__pt_cond_{i}", ["__pt_c"],
                      [unpack, ast.Return(node.test)])
        bdef = _fndef(f"__pt_body_{i}", ["__pt_c"],
                      [unpack] + list(node.body)
                      + [ast.Return(_names_tuple(names, ast.Load))])
        assign = ast.Assign(
            targets=[_names_tuple(names, ast.Store)],
            value=ast.Call(ast.Name("__pt_cvt_while", ast.Load()),
                           [ast.Name(f"__pt_cond_{i}", ast.Load()),
                            ast.Name(f"__pt_body_{i}", ast.Load()),
                            _names_tuple(names, ast.Load)], []))
        return [cdef, bdef, assign]

    def visit_For(self, node):
        self.generic_visit(node)
        if (node.orelse or not _straight_line(node.body)
                or not isinstance(node.target, ast.Name)
                or not (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range"
                        and len(node.iter.args) == 1)):
            return node
        names = sorted(_collect_assigned(node.body) - {node.target.id})
        if not names:
            return node
        i = self.n
        self.n += 1
        self.converted += 1
        unpack = self._unpack(names, "__pt_c")
        bind_i = ast.Assign(targets=[ast.Name(node.target.id, ast.Store())],
                            value=ast.Name("__pt_i", ast.Load()))
        bdef = _fndef(f"__pt_body_{i}", ["__pt_i", "__pt_c"],
                      [unpack, bind_i] + list(node.body)
                      + [ast.Return(_names_tuple(names, ast.Load))])
        assign = ast.Assign(
            targets=[_names_tuple(names, ast.Store)],
            value=ast.Call(ast.Name("__pt_cvt_for", ast.Load()),
                           [node.iter.args[0],
                            ast.Name(f"__pt_body_{i}", ast.Load()),
                            _names_tuple(names, ast.Load)], []))
        return [bdef, assign]


def convert_control_flow(fn):
    """AST-convert simple tensor-conditioned if/while/for in `fn`.
    Returns the converted function, or None when nothing was (or could be)
    converted — closures, unavailable source, or no convertible statements."""
    if getattr(fn, "__code__", None) is None or fn.__code__.co_freevars:
        return None
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, ast.FunctionDef):
        return None
    fdef.decorator_list = []  # don't re-apply @to_static etc.
    tr = _Transformer()
    tree = tr.visit(tree)
    if tr.converted == 0:
        return None
    ast.fix_missing_locations(tree)
    code = compile(tree, f"<dy2static:{fn.__name__}>", "exec")
    ns = dict(fn.__globals__)
    ns.update(_HELPERS)
    exec(code, ns)
    out = ns[fdef.name]
    out.__dy2static_converted__ = True
    return out
