"""dy2static control-flow conversion (AST passes) + guided errors.

Reference parity: paddle.jit's SOT bytecode capture (jit/sot/translate.py:32)
and the AST dy2static package (jit/dy2static/ — in particular the
return/break/continue transformers) convert data-dependent Python control
flow (`if tensor:`, `while tensor:`, `for i in range(tensor):`) into graph
ops. TPU-native design: capture-by-trace makes ordinary Python the
translator, so only DATA-DEPENDENT control flow needs help. Pieces:

1. Detection: `Tensor.__bool__` under a jax trace raises
   `Dy2StaticControlFlowError` naming `paddle.jit.cond/while_loop` (instead
   of jax's tracer-leak message).
2. Conversion: `convert_control_flow(fn)` runs three AST passes and compiles
   the result (reference jit/dy2static analogs in parentheses):
   a. loop-exit rewriting (break_continue_transformer / return_transformer):
      `break`/`continue`/`return` inside `while`/`for range` loops become
      boolean flags threaded through the loop carry — the loop test gains
      `not break_flag`, statements after a flag-set are predicated, and a
      `return` exits the loop and re-raises as a post-loop early return;
      `for range` loops with exits are first rewritten into `while` form;
   b. early-return splitting (return_transformer): an `if` containing
      `return` is rewritten so both branches end in a return (the statements
      AFTER the if are duplicated into the fall-through branch), then the
      branch returns become assignments of one `__pt_rv_*` local and a
      single `return` follows — the `if` is now a plain assigning branch;
   c. the branch converter: tensor-conditioned `if`/`while`/`for range`
      statements — now including NESTED converted blocks — become
      `lax.cond` / `lax.while_loop` / `lax.fori_loop` calls over the
      assigned locals.
   `StaticFunction.__call__` retries with the converted function when the
   first trace hits the detection error; unconvertible functions (yield,
   raise, non-tensor carried locals, structure-mismatched returns) re-raise
   the guided message naming the offending local where possible.
"""
from __future__ import annotations

import ast
import inspect
import textwrap

import jax
import jax.numpy as jnp

__all__ = ["Dy2StaticControlFlowError", "convert_control_flow"]

GUIDANCE = (
    "data-dependent Python control flow reached a traced Tensor "
    "(`if`/`while` on a tensor value, or bool() during jit/to_static "
    "capture). Rewrite with paddle_tpu.jit.cond / paddle_tpu.jit.while_loop "
    "/ paddle_tpu.jit.scan (compiled lax control flow), or keep the branch "
    "simple (straight-line assignments only) so to_static's dy2static AST "
    "pass can convert it automatically."
)


class Dy2StaticControlFlowError(TypeError):
    pass


# --------------------------------------------------------------------------
# runtime helpers injected into converted functions


def _v(x):
    from paddle_tpu.core.tensor import Tensor

    return x._value if isinstance(x, Tensor) else x


def _is_traced(x):
    return isinstance(_v(x), jax.core.Tracer)


def _wrap_out(vals):
    from paddle_tpu.core.tensor import Tensor

    return tuple(Tensor(v) if isinstance(v, (jax.Array, jnp.ndarray))
                 or isinstance(v, jax.core.Tracer) else v for v in vals)


def _unwrap_tuple(t, names=None):
    """Array-ify carried locals; a non-tensor local raises the GUIDED error
    naming the variable instead of an opaque jax failure (advisor r4)."""
    out = []
    for i, x in enumerate(t):
        try:
            out.append(jnp.asarray(_v(x)))
        except (TypeError, ValueError) as e:
            name = names[i] if names and i < len(names) else f"#{i}"
            raise Dy2StaticControlFlowError(
                f"dy2static: local '{name}' holds a non-tensor value "
                f"({type(x).__name__}: {x!r}) across converted control "
                f"flow, which cannot be carried through lax.cond/"
                f"while_loop. {GUIDANCE}") from e
    return tuple(out)


def _pt_cvt_if(cond, true_fn, false_fn, env, names=None):
    if not _is_traced(cond):
        return true_fn(env) if bool(_v(cond)) else false_fn(env)

    def br(fn):
        def g(_):
            return _unwrap_tuple(fn(env), names)

        return g

    try:
        outs = jax.lax.cond(jnp.asarray(_v(cond)).astype(bool),
                            br(true_fn), br(false_fn), None)
    except TypeError as e:
        if isinstance(e, Dy2StaticControlFlowError):
            raise
        raise Dy2StaticControlFlowError(
            f"dy2static: converted branches of `if` produce mismatched "
            f"shapes/types for locals {list(names or [])} "
            f"({e}). {GUIDANCE}") from e
    return _wrap_out(outs)


def _pt_cvt_while(cond_fn, body_fn, carry, names=None):
    probe = cond_fn(tuple(carry))
    if not _is_traced(probe) and not any(_is_traced(c) for c in carry):
        carry = tuple(carry)
        while bool(_v(cond_fn(carry))):
            carry = tuple(body_fn(carry))
        return carry

    def c(cu):
        return jnp.asarray(_v(cond_fn(_wrap_out(cu)))).astype(bool)

    def b(cu):
        return _unwrap_tuple(body_fn(_wrap_out(cu)), names)

    try:
        outs = jax.lax.while_loop(c, b, _unwrap_tuple(carry, names))
    except TypeError as e:
        if isinstance(e, Dy2StaticControlFlowError):
            raise
        raise Dy2StaticControlFlowError(
            f"dy2static: converted `while` carry changes shape/type across "
            f"iterations for locals {list(names or [])} ({e}). "
            f"{GUIDANCE}") from e
    return _wrap_out(outs)


def _pt_cvt_for(n, body_fn, carry, names=None):
    if not _is_traced(n):
        carry = tuple(carry)
        for i in range(int(_v(n))):
            carry = tuple(body_fn(i, carry))
        return carry

    def b(i, cu):
        from paddle_tpu.core.tensor import Tensor

        return _unwrap_tuple(body_fn(Tensor(i), _wrap_out(cu)), names)

    outs = jax.lax.fori_loop(0, jnp.asarray(_v(n)).astype(jnp.int32),
                             b, _unwrap_tuple(carry, names))
    return _wrap_out(outs)


def _pt_and_not(flag, value):
    """`(not flag) and value` with tensor semantics (loop-exit flags)."""
    f = jnp.asarray(_v(flag)).astype(bool)
    v = jnp.asarray(_v(value)).astype(bool)
    return jnp.logical_and(jnp.logical_not(f), v)


def _pt_or(a, b):
    return jnp.logical_or(jnp.asarray(_v(a)).astype(bool),
                          jnp.asarray(_v(b)).astype(bool))


def _pt_not(a):
    return jnp.logical_not(jnp.asarray(_v(a)).astype(bool))


def _pt_zeros_like(x):
    """Shape/dtype seed for a loop-carried early-return value."""
    return jnp.zeros_like(jnp.asarray(_v(x)))


def _pt_seed_fail(e):
    raise Dy2StaticControlFlowError(
        "dy2static: a `return` inside a converted loop or branch must "
        "return a value derivable from locals defined BEFORE the construct "
        f"(its shape seeds the carry); evaluating the seed failed with "
        f"{type(e).__name__}: {e}. " + GUIDANCE)


_HELPERS = {"__pt_cvt_if": _pt_cvt_if, "__pt_cvt_while": _pt_cvt_while,
            "__pt_cvt_for": _pt_cvt_for, "__pt_and_not": _pt_and_not,
            "__pt_or": _pt_or, "__pt_not": _pt_not,
            "__pt_zeros_like": _pt_zeros_like,
            "__pt_seed_fail": _pt_seed_fail}


# --------------------------------------------------------------------------
# the AST pass


def _collect_assigned(stmts) -> set:
    names = set()

    def tgt(t):
        if isinstance(t, ast.Name):
            names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                tgt(e)

    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    tgt(t)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                tgt(node.target)
    return names


def _straight_line(stmts) -> bool:
    """No exotic control flow. Generated __pt_* defs (already-converted
    NESTED control flow) are opaque and fine — their bodies are not
    descended into; user-defined inner defs are rejected."""
    for s in stmts:
        stack = [s]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not node.name.startswith("__pt_"):
                    return False
                continue  # converted-subtree internals are fine
            if isinstance(node, (ast.Return, ast.Break, ast.Continue,
                                 ast.Yield, ast.YieldFrom, ast.Raise,
                                 ast.Lambda, ast.Global, ast.Nonlocal)):
                return False
            stack.extend(ast.iter_child_nodes(node))
    return True


# --------------------------------------------------------------------------
# pass a: loop-exit rewriting (reference jit/dy2static break_continue_
# transformer + return_transformer) — break/continue/return inside loops
# become carried boolean flags with predicated continuation


def _call(fname, args):
    return ast.Call(ast.Name(fname, ast.Load()), args, [])


def _assign(name, value):
    return ast.Assign(targets=[ast.Name(name, ast.Store())], value=value)


def _sets_flag(stmt, flags) -> bool:
    for node in ast.walk(stmt):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Name) and t.id in flags
                        and isinstance(node.value, ast.Constant)
                        and node.value.value is True):
                    return True
    return False


def _guard_rest(stmts, flags):
    """Predicate statements that follow a possible flag-set: the rest of the
    block runs under `if not (f1 or f2 ...):` (tensor-safe helper calls)."""
    out = []
    for i, s in enumerate(stmts):
        out.append(s)
        if _sets_flag(s, flags) and i + 1 < len(stmts):
            test = ast.Name(sorted(flags)[0], ast.Load())
            for f in sorted(flags)[1:]:
                test = _call("__pt_or", [test, ast.Name(f, ast.Load())])
            test = _call("__pt_not", [test])
            out.append(ast.If(test=test,
                              body=_guard_rest(stmts[i + 1:], flags),
                              orelse=[]))
            return out
    return out


def _guard_deep(stmts, flags):
    """_guard_rest applied recursively inside if-branches (a statement after
    `break`/`continue` INSIDE the same branch must be predicated too); does
    not descend into nested loops or defs — their exits are their own."""
    rewritten = []
    for s in stmts:
        if isinstance(s, ast.If):
            rewritten.append(ast.If(test=s.test,
                                    body=_guard_deep(s.body, flags),
                                    orelse=_guard_deep(s.orelse, flags)))
        else:
            rewritten.append(s)
    return _guard_rest(rewritten, flags)


def _rewrite_exits(stmts, brk, cont, retf, rv, state):
    """Replace break/continue/return belonging to THIS loop level (recursion
    stops at nested loops / function defs)."""
    out = []
    for s in stmts:
        if isinstance(s, ast.Break):
            state["brk"] = True
            out.append(_assign(brk, ast.Constant(True)))
        elif isinstance(s, ast.Continue):
            state["cont"] = True
            out.append(_assign(cont, ast.Constant(True)))
        elif isinstance(s, ast.Return):
            state["ret"] = True
            val = s.value if s.value is not None else ast.Constant(None)
            if "ret_expr" not in state:
                import copy as _copy

                state["ret_expr"] = _copy.deepcopy(val)
            out.append(_assign(rv, val))
            out.append(_assign(retf, ast.Constant(True)))
            out.append(_assign(brk, ast.Constant(True)))
        elif isinstance(s, ast.If):
            out.append(ast.If(
                test=s.test,
                body=_rewrite_exits(s.body, brk, cont, retf, rv, state),
                orelse=_rewrite_exits(s.orelse, brk, cont, retf, rv, state)))
        elif isinstance(s, (ast.While, ast.For, ast.FunctionDef,
                            ast.AsyncFunctionDef)):
            out.append(s)  # exits inside belong to the inner construct
        else:
            out.append(s)
    return out


class _LoopExitPass(ast.NodeTransformer):
    """Bottom-up: rewrite while/for-range loops containing break/continue/
    return into flag-carried whiles; a loop return re-raises as a post-loop
    early return (consumed by the split pass)."""

    def __init__(self):
        self.k = 0

    def _loop_has_exit(self, body) -> bool:
        found = [False]

        def walk(stmts):
            for s in stmts:
                if isinstance(s, (ast.Break, ast.Continue, ast.Return)):
                    found[0] = True
                elif isinstance(s, ast.If):
                    walk(s.body)
                    walk(s.orelse)
        walk(body)
        return found[0]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or not self._loop_has_exit(node.body):
            return node
        return self._rewrite(node.test, node.body)

    def visit_For(self, node):
        self.generic_visit(node)
        if (node.orelse or not self._loop_has_exit(node.body)
                or not isinstance(node.target, ast.Name)
                or not (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range"
                        and len(node.iter.args) == 1)):
            return node
        # for x in range(n) with exits -> while form (index incremented at
        # iteration START so `continue` cannot skip it)
        k = self.k
        iname, nname = f"__pt_fi_{k}", f"__pt_fn_{k}"
        body = ([_assign(node.target.id, ast.Name(iname, ast.Load())),
                 _assign(iname, ast.BinOp(ast.Name(iname, ast.Load()),
                                          ast.Add(), ast.Constant(1)))]
                + list(node.body))
        test = ast.Compare(ast.Name(iname, ast.Load()), [ast.Lt()],
                           [ast.Name(nname, ast.Load())])
        pre = [_assign(iname, ast.Constant(0)),
               _assign(nname, node.iter.args[0])]
        return pre + self._rewrite(test, body)

    def _rewrite(self, test, body):
        k = self.k
        self.k += 1
        brk, cont = f"__pt_brk_{k}", f"__pt_cont_{k}"
        retf, rv = f"__pt_lret_{k}", f"__pt_lrv_{k}"
        state = {}
        body = _rewrite_exits(body, brk, cont, retf, rv, state)
        flags = set()
        if state.get("brk") or state.get("ret"):
            flags.add(brk)
        if state.get("cont"):
            flags.add(cont)
        body = _guard_deep(body, flags)
        if state.get("cont"):
            body = [_assign(cont, ast.Constant(False))] + body
        new_test = (_call("__pt_and_not",
                          [ast.Name(brk, ast.Load()), test])
                    if brk in flags else test)
        pre = [_assign(brk, ast.Constant(False))]
        if state.get("cont"):
            pre.append(_assign(cont, ast.Constant(False)))
        post = []
        if state.get("ret"):
            pre.append(_assign(retf, ast.Constant(False)))
            seed = _assign(rv, _call("__pt_zeros_like", [state["ret_expr"]]))
            handler = ast.ExceptHandler(
                type=ast.Name("Exception", ast.Load()), name="__pt_e",
                body=[ast.Expr(_call("__pt_seed_fail",
                                     [ast.Name("__pt_e", ast.Load())]))])
            pre.append(ast.Try(body=[seed], handlers=[handler], orelse=[],
                               finalbody=[]))
            post = [ast.If(test=ast.Name(retf, ast.Load()),
                           body=[ast.Return(ast.Name(rv, ast.Load()))],
                           orelse=[])]
        return pre + [ast.While(test=new_test, body=body, orelse=[])] + post


# --------------------------------------------------------------------------
# pass b: early-return splitting (reference return_transformer) — an `if`
# containing `return` absorbs the statements that follow it into its
# fall-through paths, then every path's return becomes one local assignment


def _has_return(stmts) -> bool:
    for s in stmts:
        if isinstance(s, ast.Return):
            return True
        if isinstance(s, ast.If):
            if _has_return(s.body) or _has_return(s.orelse):
                return True
    return False


def _ends_return(stmts) -> bool:
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If):
        return _ends_return(last.body) and _ends_return(last.orelse)
    return False


def _returns_to_assign(stmts, rv, rf=None):
    """Map every `return X` to `rv = X` (plus `rf = True` when a return
    flag is threaded)."""
    out = []
    for s in stmts:
        if isinstance(s, ast.Return):
            out.append(_assign(
                rv, s.value if s.value is not None else ast.Constant(None)))
            if rf is not None:
                out.append(_assign(rf, ast.Constant(True)))
        elif isinstance(s, ast.If):
            out.append(ast.If(test=s.test,
                              body=_returns_to_assign(s.body, rv, rf),
                              orelse=_returns_to_assign(s.orelse, rv, rf)))
        else:
            out.append(s)
    return out


def _flag_returns(stmts, rv, rf):
    """Convert the body of a branch whose fall-through continues in the
    ENCLOSING scope: every `return X` becomes `rv = X; rf = True`, and the
    statements after a maybe-returning `if` are predicated on `not rf`.
    Unlike `_split_returns`, fall-through does NOT return None — it simply
    leaves rf unset so the enclosing scope's trailing code runs. Each
    trailing suffix is emitted once (linear total size)."""
    out = []
    for i, s in enumerate(stmts):
        if isinstance(s, ast.Return):
            out.append(_assign(
                rv, s.value if s.value is not None else ast.Constant(None)))
            out.append(_assign(rf, ast.Constant(True)))
            return out  # anything after a return is unreachable
        if isinstance(s, ast.If) and (_has_return(s.body)
                                      or _has_return(s.orelse)):
            out.append(ast.If(test=s.test,
                              body=_flag_returns(s.body, rv, rf),
                              orelse=_flag_returns(s.orelse, rv, rf)))
            rest = stmts[i + 1:]
            if rest:
                out.append(ast.If(
                    test=_call("__pt_not", [ast.Name(rf, ast.Load())]),
                    body=_flag_returns(rest, rv, rf), orelse=[]))
            return out
        out.append(s)
    return out


def _first_return_expr(stmts):
    for s in stmts:
        if isinstance(s, ast.Return) and s.value is not None:
            return s.value
        if isinstance(s, ast.If):
            e = _first_return_expr(s.body) or _first_return_expr(s.orelse)
            if e is not None:
                return e
    return None


def _seed_needs_branch_locals(seed_expr, tb, fb) -> bool:
    """True when `seed_expr` reads a name assigned inside the branch bodies
    — evaluating zeros_like(seed_expr) BEFORE the branch would then hit an
    unbound local. Conservative: any store anywhere in either branch."""
    local = set()
    for s in tb + fb:
        for node in ast.walk(s):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                local.add(node.id)
    return any(isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
               and n.id in local for n in ast.walk(seed_expr))


def _split_returns(stmts, counter):
    out = []
    for i, s in enumerate(stmts):
        if isinstance(s, ast.If) and (_has_return(s.body)
                                      or _has_return(s.orelse)):
            rest = stmts[i + 1:]
            j = counter[0]
            counter[0] += 1
            rv = f"__pt_frv_{j}"
            rf = f"__pt_frf_{j}"
            tb = list(s.body)
            fb = list(s.orelse)
            t_ret, f_ret = _ends_return(tb), _ends_return(fb)
            if rest and (t_ret or f_ret):
                # guard-clause shape: MOVE the trailing statements into the
                # one fall-through branch (emitted once — the old deep-copy
                # into both branches cost O(2^N) for N sequential guards);
                # the chain converts to nested if/else of linear total size
                if not t_ret:
                    tb += rest
                elif not f_ret:
                    fb += rest
                rest = []
            if not rest:
                # a fall-through path returns None (eager semantics);
                # carrying None through lax.cond fails with the GUIDED
                # non-tensor error rather than silently substituting a value
                if not _ends_return(tb):
                    tb.append(ast.Return(ast.Constant(None)))
                if not _ends_return(fb):
                    fb.append(ast.Return(ast.Constant(None)))
                tb = _returns_to_assign(_split_returns(tb, counter), rv)
                fb = _returns_to_assign(_split_returns(fb, counter), rv)
                out.append(ast.If(test=s.test, body=tb, orelse=fb))
                out.append(ast.Return(ast.Name(rv, ast.Load())))
                return out
            # BOTH branches fall through (returns only nested deeper): the
            # trailing statements are emitted ONCE, predicated on a return
            # flag. rv is seeded zeros_like(first return expr) — the loop
            # pass's carry-seed idiom — so the converted cond carries a
            # type-consistent value on the not-yet-returned path
            import copy as _copy

            seed_expr = _first_return_expr(tb) or _first_return_expr(fb)
            if seed_expr is not None and _seed_needs_branch_locals(
                    seed_expr, tb, fb):
                # the seed reads branch-local names, so it cannot evaluate
                # before the branch: fall back to the deep-copy split (the
                # pre-flag shape — quadratic only across consecutive such
                # ifs, which guard-clause chains never produce)
                tb += [_copy.deepcopy(r) for r in rest]
                fb += [_copy.deepcopy(r) for r in rest]
                if not _ends_return(tb):
                    tb.append(ast.Return(ast.Constant(None)))
                if not _ends_return(fb):
                    fb.append(ast.Return(ast.Constant(None)))
                tb = _returns_to_assign(_split_returns(tb, counter), rv)
                fb = _returns_to_assign(_split_returns(fb, counter), rv)
                out.append(ast.If(test=s.test, body=tb, orelse=fb))
                out.append(ast.Return(ast.Name(rv, ast.Load())))
                return out
            # branch fall-through continues at the trailing statements, so
            # the branches convert with _flag_returns (NOT the function-
            # level _split_returns, whose fall-through returns None)
            tb = _flag_returns(tb, rv, rf)
            fb = _flag_returns(fb, rv, rf)
            out.append(_assign(rf, ast.Constant(False)))
            if seed_expr is None:
                out.append(_assign(rv, ast.Constant(None)))
            else:
                seed = _assign(rv, _call("__pt_zeros_like",
                                         [_copy.deepcopy(seed_expr)]))
                handler = ast.ExceptHandler(
                    type=ast.Name("Exception", ast.Load()), name="__pt_e",
                    body=[ast.Expr(_call("__pt_seed_fail",
                                         [ast.Name("__pt_e", ast.Load())]))])
                out.append(ast.Try(body=[seed], handlers=[handler],
                                   orelse=[], finalbody=[]))
            out.append(ast.If(test=s.test, body=tb, orelse=fb))
            rest_s = _split_returns(list(rest), counter)
            ends = _ends_return(rest_s)
            rest_t = _returns_to_assign(rest_s, rv, rf)
            if not ends:
                rest_t.append(_assign(rv, ast.Constant(None)))
            out.append(ast.If(
                test=_call("__pt_not", [ast.Name(rf, ast.Load())]),
                body=rest_t, orelse=[]))
            out.append(ast.Return(ast.Name(rv, ast.Load())))
            return out
        out.append(s)
    return out


def _names_tuple(names, ctx):
    return ast.Tuple([ast.Name(n, ctx()) for n in names], ctx())


def _fndef(name, argnames, body):
    args = ast.arguments(posonlyargs=[], args=[ast.arg(a) for a in argnames],
                         vararg=None, kwonlyargs=[], kw_defaults=[],
                         kwarg=None, defaults=[])
    return ast.FunctionDef(name=name, args=args, body=body,
                           decorator_list=[], returns=None, type_params=[])


class _Transformer(ast.NodeTransformer):
    def __init__(self):
        self.n = 0
        self.converted = 0

    def _unpack(self, names, src_name):
        return ast.Assign(
            targets=[_names_tuple(names, ast.Store)],
            value=ast.Name(src_name, ast.Load()))

    def visit_If(self, node):
        self.generic_visit(node)
        if not (_straight_line(node.body) and _straight_line(node.orelse)):
            return node
        names = sorted(_collect_assigned(node.body)
                       | _collect_assigned(node.orelse))
        if not names:
            return node
        i = self.n
        self.n += 1
        self.converted += 1
        # branch defs take the enclosing locals() so names read-then-assigned
        # inside a branch see their current outer values
        prelude = [ast.Assign(
            targets=[ast.Name(n, ast.Store())],
            value=ast.Call(
                ast.Attribute(ast.Name("__pt_env", ast.Load()), "get",
                              ast.Load()),
                [ast.Constant(n)], [])) for n in names]
        ret = ast.Return(_names_tuple(names, ast.Load))
        tdef = _fndef(f"__pt_true_{i}", ["__pt_env"],
                      prelude + list(node.body) + [ret])
        fdef = _fndef(f"__pt_false_{i}", ["__pt_env"],
                      prelude + (list(node.orelse) or [ast.Pass()]) + [ret])
        assign = ast.Assign(
            targets=[_names_tuple(names, ast.Store)],
            value=ast.Call(ast.Name("__pt_cvt_if", ast.Load()),
                           [node.test,
                            ast.Name(f"__pt_true_{i}", ast.Load()),
                            ast.Name(f"__pt_false_{i}", ast.Load()),
                            ast.Call(ast.Name("locals", ast.Load()), [], []),
                            ast.Tuple([ast.Constant(n) for n in names],
                                      ast.Load())],
                           []))
        return [tdef, fdef, assign]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or not _straight_line(node.body):
            return node
        names = sorted(_collect_assigned(node.body))
        if not names:
            return node
        i = self.n
        self.n += 1
        self.converted += 1
        unpack = self._unpack(names, "__pt_c")
        cdef = _fndef(f"__pt_cond_{i}", ["__pt_c"],
                      [unpack, ast.Return(node.test)])
        bdef = _fndef(f"__pt_body_{i}", ["__pt_c"],
                      [unpack] + list(node.body)
                      + [ast.Return(_names_tuple(names, ast.Load))])
        assign = ast.Assign(
            targets=[_names_tuple(names, ast.Store)],
            value=ast.Call(ast.Name("__pt_cvt_while", ast.Load()),
                           [ast.Name(f"__pt_cond_{i}", ast.Load()),
                            ast.Name(f"__pt_body_{i}", ast.Load()),
                            _names_tuple(names, ast.Load),
                            ast.Tuple([ast.Constant(n) for n in names],
                                      ast.Load())], []))
        return [cdef, bdef, assign]

    def visit_For(self, node):
        self.generic_visit(node)
        if (node.orelse or not _straight_line(node.body)
                or not isinstance(node.target, ast.Name)
                or not (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range"
                        and len(node.iter.args) == 1)):
            return node
        names = sorted(_collect_assigned(node.body) - {node.target.id})
        if not names:
            return node
        i = self.n
        self.n += 1
        self.converted += 1
        unpack = self._unpack(names, "__pt_c")
        bind_i = ast.Assign(targets=[ast.Name(node.target.id, ast.Store())],
                            value=ast.Name("__pt_i", ast.Load()))
        bdef = _fndef(f"__pt_body_{i}", ["__pt_i", "__pt_c"],
                      [unpack, bind_i] + list(node.body)
                      + [ast.Return(_names_tuple(names, ast.Load))])
        assign = ast.Assign(
            targets=[_names_tuple(names, ast.Store)],
            value=ast.Call(ast.Name("__pt_cvt_for", ast.Load()),
                           [node.iter.args[0],
                            ast.Name(f"__pt_body_{i}", ast.Load()),
                            _names_tuple(names, ast.Load),
                            ast.Tuple([ast.Constant(n) for n in names],
                                      ast.Load())], []))
        return [bdef, assign]


def convert_control_flow(fn):
    """AST-convert simple tensor-conditioned if/while/for in `fn`.
    Returns the converted function, or None when nothing was (or could be)
    converted — closures, unavailable source, or no convertible statements."""
    if getattr(fn, "__code__", None) is None or fn.__code__.co_freevars:
        return None
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, ast.FunctionDef):
        return None
    fdef.decorator_list = []  # don't re-apply @to_static etc.
    # pass a: loop exits -> carried flags; pass b: early returns -> one
    # assigned local per split point (reference jit/dy2static transformers)
    fdef = _LoopExitPass().visit(fdef)
    fdef.body = _split_returns(fdef.body, [0])
    tree.body[0] = fdef
    tr = _Transformer()
    tree = tr.visit(tree)
    if tr.converted == 0:
        return None
    ast.fix_missing_locations(tree)
    code = compile(tree, f"<dy2static:{fn.__name__}>", "exec")
    ns = dict(fn.__globals__)
    ns.update(_HELPERS)
    exec(code, ns)
    out = ns[fdef.name]
    out.__dy2static_converted__ = True
    return out
