"""paddle.linalg namespace (reference: python/paddle/linalg.py — re-exports
the tensor linalg ops under one module)."""
from paddle_tpu.ops.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, cross, det, eig, eigh,
    eigvals, eigvalsh, householder_product, inv, lstsq, lu, lu_unpack,
    matmul, matrix_exp, matrix_power, matrix_rank, multi_dot, norm, pinv, qr,
    slogdet, solve, svd, triangular_solve,
)

__all__ = ["cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "cross",
           "det", "eig", "eigh", "eigvals", "eigvalsh", "householder_product",
           "inv", "lstsq", "lu", "lu_unpack", "matmul", "matrix_exp",
           "matrix_power", "matrix_rank", "multi_dot", "norm", "pinv", "qr",
           "slogdet", "solve", "svd", "triangular_solve"]
