"""paddle.linalg namespace (reference: python/paddle/linalg.py — re-exports
the tensor linalg ops under one module)."""
from paddle_tpu.ops.linalg import (  # noqa: F401
    cholesky, cond, cross, det, eig, eigh, eigvals, eigvalsh, inv, lstsq, lu,
    matmul, matrix_power, matrix_rank, multi_dot, norm, pinv, qr, slogdet,
    solve, svd, triangular_solve,
)

__all__ = ["cholesky", "cond", "cross", "det", "eig", "eigh", "eigvals",
           "eigvalsh", "inv", "lstsq", "lu", "matmul", "matrix_power",
           "matrix_rank", "multi_dot", "norm", "pinv", "qr", "slogdet",
           "solve", "svd", "triangular_solve"]
