"""Multi-tenant LoRA: adapter train -> export -> serve on one base model.

Lazy by design (PEP 562): `nn.functional` imports `paddle_tpu.lora.seam`
at module load to hook the `F.linear` dispatch seam, so this package
must not eagerly pull in the adapter/store stacks (inference.artifact,
observability, resilience) — attribute access resolves them on demand.
"""
from __future__ import annotations

from paddle_tpu.lora import seam  # light: stdlib + lazy jax

__all__ = ["seam", "LoRAConfig", "LoRAAdapter", "attach", "detach",
           "export_adapter", "load_adapter", "find_targets",
           "DEFAULT_TARGETS", "AdapterStore", "AdapterLoadError"]

_ADAPTER = ("LoRAConfig", "LoRAAdapter", "attach", "detach",
            "export_adapter", "load_adapter", "find_targets",
            "DEFAULT_TARGETS")
_STORE = ("AdapterStore", "AdapterLoadError")


def __getattr__(name):
    if name in _ADAPTER:
        from paddle_tpu.lora import adapter
        return getattr(adapter, name)
    if name in _STORE:
        from paddle_tpu.lora import store
        return getattr(store, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(__all__)
