"""LoRA adapter definition + frozen-base training + artifact export.

The adapter math (Hu et al.): a target projection ``y = x @ W`` gains a
rank-``r`` update ``y = x @ W + (alpha / r) * (x @ A) @ B`` with
``A [d_in, r]`` (small random init) and ``B [r, d_out]`` (zeros — the
delta starts at exactly zero, so attaching is a no-op until training
moves B). W stays FROZEN: `attach()` flips every base parameter's
``stop_gradient`` on, so a `CompiledTrainStep` built afterwards computes
gradients and allocates optimizer moments for the adapter factors ONLY
(base params ride through its donated buffers read-only).

Attachment is by dispatch seam, not by module surgery: A/B register in
`lora.seam` keyed by ``id(weight)`` and `F.linear` adds the delta for
any projection whose weight is adapted — `ColumnParallelLinear`,
`RowParallelLinear` and plain `nn.Linear` all route through that one
seam, so no model rewrite is needed. The factors also land on the model
as a ``_lora_host`` sublayer, which puts them in ``model.parameters()``
(what `CompiledTrainStep` packs) and in checkpoints.

Export writes a tiny `paddle_tpu-npz1` container (inference/artifact.py)
holding ONLY the A/B factors plus an ``adapter`` meta block — no
stablehlo program, no base weights: thousands of per-customer adapters
stay kilobytes each against one shared base.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from paddle_tpu.lora import seam

__all__ = ["LoRAConfig", "LoRAAdapter", "attach", "detach",
           "export_adapter", "load_adapter", "find_targets",
           "DEFAULT_TARGETS"]

DEFAULT_TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj")


@dataclass
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    targets: tuple = DEFAULT_TARGETS
    dtype: object = None          # None -> each target weight's dtype
    seed: int = 0

    @property
    def scale(self) -> float:
        return float(self.alpha) / float(self.rank)


def find_targets(model, targets):
    """Deterministic (traversal-order) list of ``(path, weight Parameter)``
    for every sublayer whose attribute name matches a target projection
    and that carries a 2-D ``weight`` — the shared discovery both
    `attach()` (training) and the serving `AdapterStore` run, so exported
    factor order lines up with the store's pool order by construction."""
    found = []
    seen = set()
    for path, sub in model.named_sublayers():
        if path.rsplit(".", 1)[-1] not in targets:
            continue
        w = getattr(sub, "weight", None)
        if w is None or getattr(w, "ndim", 0) != 2 or id(w) in seen:
            continue
        seen.add(id(w))
        found.append((path, w))
    if not found:
        raise ValueError(
            f"no LoRA target projections found: none of {tuple(targets)} "
            f"name a sublayer with a 2-D weight on {type(model).__name__}")
    return found


@dataclass
class LoRAAdapter:
    """Handle returned by `attach()`: the adapted weights and their A/B
    factors, plus what `detach()` needs to restore the model exactly."""
    model: object
    config: LoRAConfig
    entries: list = field(default_factory=list)   # (path, weight, A, B)
    _frozen: list = field(default_factory=list)   # (param, prior stop_gradient)
    _attached: bool = True

    def parameters(self):
        out = []
        for _, _, a, b in self.entries:
            out.extend((a, b))
        return out

    def export(self, path: str, adapter_id: str = "adapter"):
        export_adapter(path, self, adapter_id=adapter_id)

    def load_weights(self, blob: dict):
        """Overwrite the attached factors from a `load_adapter()` blob
        (rank/targets validated) — resume or A/B-swap during training."""
        meta, weights = blob["adapter"], blob["weights"]
        if int(meta["rank"]) != int(self.config.rank):
            raise ValueError(f"adapter rank {meta['rank']} != attached "
                             f"rank {self.config.rank}")
        for path, _, a, b in self.entries:
            if path not in weights:
                raise ValueError(f"adapter blob is missing factors for "
                                 f"target {path!r}")
            av, bv = weights[path]
            a.set_value(np.asarray(av))
            b.set_value(np.asarray(bv))


def attach(model, config: LoRAConfig | None = None,
           freeze_base: bool = True) -> LoRAAdapter:
    """Attach rank-``r`` factors to every target projection and (by
    default) freeze the base: afterwards ``model.parameters()`` is the
    frozen base plus the trainable A/B factors, and a `CompiledTrainStep`
    built from it trains ONLY the adapter (its optimizer state is sized
    to the adapter — train_step keeps no moments for frozen entries).
    Detach before serving the base through an `AdapterStore`."""
    from paddle_tpu.nn.layer.layers import Layer, Parameter

    cfg = config or LoRAConfig()
    if int(cfg.rank) <= 0:
        raise ValueError(f"LoRA rank must be positive, got {cfg.rank}")
    if getattr(model, "_lora_host", None) is not None:
        raise ValueError("model already has a LoRA adapter attached; "
                         "detach() it first")
    handle = LoRAAdapter(model=model, config=cfg)
    if freeze_base:
        for p in model.parameters():
            handle._frozen.append((p, p.stop_gradient))
            p.stop_gradient = True
    rng = np.random.default_rng(cfg.seed)
    host = Layer()
    for i, (path, w) in enumerate(find_targets(model, cfg.targets)):
        d_in, d_out = int(w.shape[0]), int(w.shape[1])
        if cfg.dtype is None:
            dt = np.dtype(w._value.dtype)
        elif isinstance(cfg.dtype, str):
            from paddle_tpu.inference.artifact import np_dtype
            dt = np_dtype(cfg.dtype)      # "bfloat16" and friends
        else:
            dt = np.dtype(cfg.dtype)
        # A: small random (the delta needs a non-degenerate input
        # projection); B: zeros, so attach is exactly a no-op at step 0
        a_np = (rng.standard_normal((d_in, cfg.rank))
                * (1.0 / max(cfg.rank, 1)))
        A = Parameter(a_np.astype(dt), trainable=True, name=f"lora_a_{i}")
        B = Parameter(np.zeros((cfg.rank, d_out), dt), trainable=True,
                      name=f"lora_b_{i}")
        setattr(host, f"a_{i}", A)
        setattr(host, f"b_{i}", B)
        handle.entries.append((path, w, A, B))
        seam.train_register(id(w), seam.TrainEntry(A, B, cfg.scale))
    model._lora_host = host
    return handle


def detach(handle: LoRAAdapter):
    """Remove the adapter: clear the seam registry, drop the host
    sublayer (A/B leave ``model.parameters()``), restore every base
    parameter's prior ``stop_gradient``. The model is bit-identical to
    pre-attach (B started at zero and W was never written)."""
    if not handle._attached:
        return
    handle._attached = False
    seam.train_clear(id(w) for _, w, _, _ in handle.entries)
    model = handle.model
    if getattr(model, "_lora_host", None) is not None:
        model._sub_layers.pop("_lora_host", None)
        model._lora_host = None
        model._sub_layers.pop("_lora_host", None)
    for p, prior in handle._frozen:
        p.stop_gradient = prior


def export_adapter(path: str, handle: LoRAAdapter,
                   adapter_id: str = "adapter"):
    """Write the adapter as a `paddle_tpu-npz1` artifact: params are the
    interleaved ``[A_0, B_0, A_1, B_1, ...]`` factors in target order and
    meta carries the ``adapter`` block (id, rank, alpha, target names) —
    everything `AdapterStore.register()` needs to validate and place it.
    No stablehlo member: adapters are data against a shared base."""
    cfg = handle.config
    params, names = [], []
    for pth, _, a, b in handle.entries:
        params.append(np.asarray(a._value))
        params.append(np.asarray(b._value))
        names.append(pth)
    if all(not np.any(params[i]) for i in range(1, len(params), 2)):
        # every B is exactly zero — the fresh-attach state. After a
        # CompiledTrainStep run the trained factors live in the step's
        # donated device buffers until synced back to the Parameters.
        raise ValueError(
            "export_adapter: every B factor is zero (the attach-time "
            "init), so this adapter is a no-op. If you trained through "
            "CompiledTrainStep, call step.sync_params_to_model() before "
            "exporting.")
    from paddle_tpu.inference.artifact import write_artifact

    write_artifact(path, {
        "params": params,
        "class_name": type(handle.model).__name__,
        "adapter": {
            "id": str(adapter_id),
            "rank": int(cfg.rank),
            "alpha": float(cfg.alpha),
            "targets": list(cfg.targets),
            "names": names,
        },
    })


def load_adapter(path: str) -> dict:
    """Read an adapter artifact back: ``{"adapter": meta,
    "weights": {target_path: (A, B)}}``. Rejects containers without the
    ``adapter`` meta block (a full-model artifact is not an adapter)."""
    from paddle_tpu.inference.artifact import read_artifact

    blob = read_artifact(path)
    meta = blob.get("adapter")
    if not meta:
        raise ValueError(f"{path!r} is not a LoRA adapter artifact "
                         f"(no 'adapter' meta block)")
    names = list(meta.get("names", ()))
    params = blob.get("params", [])
    if len(params) != 2 * len(names):
        raise ValueError(
            f"{path!r}: adapter artifact has {len(params)} factor arrays "
            f"for {len(names)} targets (expected exactly A+B per target)")
    weights = {n: (params[2 * i], params[2 * i + 1])
               for i, n in enumerate(names)}
    return {"adapter": meta, "weights": weights}
