"""The LoRA dispatch seam: what `F.linear` consults per projection call.

Two registration planes share one lookup point:

* TRAIN plane — `adapter.attach()` registers per-weight A/B Parameters
  keyed by ``id(weight)`` (the Parameter object every forward resolves
  through ``Layer.__getattr__`` is stable, eagerly and under
  ``functional_call``'s in-place value binding). `F.linear` adds
  ``scale * (x @ A) @ B`` with A/B riding as apply_op inputs, so the
  delta differentiates like any other parameter.
* SERVE plane — a thread-local `ServeBinding` the `AdapterStore` installs
  INSIDE the engine's traced decode/verify/prefill programs: per-weight
  adapter POOLS (``[G, d_in, r]`` / ``[G, r, d_out]``) plus the per-row
  slot ids. The delta gathers each row's adapter through the grouped
  (ragged) Pallas matmul — heterogeneous adapters in one dispatch, pool
  shape static, so mixing tenants never retraces.

This module is deliberately light (stdlib + lazy jax): `nn.functional`
imports it at module load and must not drag the serving stack in.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

__all__ = ["TrainEntry", "ServeBinding", "train_register", "train_clear",
           "train_lookup", "serve_bind", "serve_binding", "active",
           "serve_delta"]


class TrainEntry:
    """One adapted weight's train-mode factors (A [in, r], B [r, out]
    Parameters) and the baked ``alpha / rank`` scale."""

    __slots__ = ("A", "B", "scale")

    def __init__(self, A, B, scale: float):
        self.A = A
        self.B = B
        self.scale = float(scale)


class ServeBinding:
    """The serve-mode view F.linear reads inside a traced program:
    ``pools[id(weight)] -> (a_pool, b_pool)`` tracers (scale pre-baked
    into b_pool rows), per-row ``slots`` (int32, one per batch row;
    ``num_slots`` marks rows without an adapter — the grouped matmul's
    trash id, zero delta), and the grouped-matmul launch knobs."""

    __slots__ = ("pools", "slots", "num_slots", "block_rows", "backend")

    def __init__(self, pools: dict, slots, num_slots: int,
                 block_rows: int = 8, backend: str = "auto"):
        self.pools = pools
        self.slots = slots
        self.num_slots = int(num_slots)
        self.block_rows = int(block_rows)
        self.backend = backend


_train_entries: dict[int, TrainEntry] = {}
_tls = threading.local()


def train_register(wid: int, entry: TrainEntry):
    _train_entries[wid] = entry


def train_clear(wids):
    for wid in wids:
        _train_entries.pop(wid, None)


def train_lookup(wid: int) -> TrainEntry | None:
    return _train_entries.get(wid)


def serve_binding() -> ServeBinding | None:
    return getattr(_tls, "binding", None)


@contextmanager
def serve_bind(binding: ServeBinding):
    prev = getattr(_tls, "binding", None)
    _tls.binding = binding
    try:
        yield binding
    finally:
        _tls.binding = prev


def active() -> bool:
    """The one-branch fast check F.linear pays when no adapter is
    attached or bound anywhere (the overwhelmingly common case)."""
    return bool(_train_entries) or getattr(_tls, "binding", None) is not None


def serve_delta(v, a_pool, b_pool, binding: ServeBinding):
    """Per-row heterogeneous adapter delta for one projection: flatten
    ``v [..., d]`` to rows, repeat the per-batch-row slot ids across the
    token dim (row-major reshape keeps row ``b*T + t`` owned by batch row
    ``b``), pad rows to the block grid with trash ids, and gather each
    row's adapter through two grouped matmuls. Exact per row for ANY slot
    mix (the pallas backend masks within blocks), so a heterogeneous
    batch is bit-equal to serving each adapter alone."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.grouped_matmul import grouped_matmul

    backend, bm = binding.backend, binding.block_rows
    if backend == "auto":
        # TPU: the real Pallas kernel over block_rows tiles. Elsewhere
        # (CPU CI, the bench's interpret path): the xla backend at
        # block_rows=1, where each row IS its own block — an exact
        # per-row w[gids[i]] gather for ANY slot mix, without paying the
        # interpret loop a (block, group) tile per distinct slot.
        if jax.default_backend() == "tpu":
            backend = "pallas"
        else:
            backend, bm = "xla", 1

    shape = v.shape
    d = shape[-1]
    m = 1
    for s in shape[:-1]:
        m *= int(s)
    rows = v.reshape(m, d)
    reps = m // binding.slots.shape[0]
    gids = jnp.repeat(binding.slots.astype(jnp.int32), reps)
    pad = (-m) % bm
    if pad:
        rows = jnp.concatenate(
            [rows, jnp.zeros((pad, d), rows.dtype)], axis=0)
        gids = jnp.concatenate(
            [gids, jnp.full((pad,), binding.num_slots, jnp.int32)], axis=0)
    h = grouped_matmul(rows.astype(a_pool.dtype), a_pool, gids,
                       block_rows=bm, backend=backend)
    out = grouped_matmul(h.astype(b_pool.dtype), b_pool, gids,
                         block_rows=bm, backend=backend)
    return out[:m].reshape(tuple(shape[:-1]) + (b_pool.shape[-1],))
