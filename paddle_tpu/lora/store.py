"""Serving-side adapter pool: host registry + HBM slots, LRU + refcounts.

The `AdapterStore` is to adapter weights what the PR-9 `PageAllocator` is
to KV pages. Registered adapters live host-side (numpy — the cold tier,
never evicted while registered); a STATIC pool of ``G`` HBM slots per
target projection (``a_pool [G, d_in, r]`` / ``b_pool [G, r, d_out]``,
the ``alpha/r`` scale pre-baked into B) backs the engine's compiled
programs. `acquire()` pins an adapter into a slot (host->HBM swap-in on
miss, timed + journaled), `release()` unpins it, and a full pool evicts
the least-recently-used refcount-0 slot — a pinned adapter is never
evicted mid-request, exactly the page refcount contract.

Because the pools are fixed-shape jit arguments and each request's slot
id rides the decode/verify signature as one more per-row array, ANY mix
of tenants runs the same compiled program: swapping, evicting and
hot-swapping adapters changes pool VALUES only — zero retraces by
construction.

Failure shape: `AdapterLoadError` is a typed PER-REQUEST error (unknown
id, exhausted pool, or the ``serving.lora.swap_fail`` chaos point below).
The engine surfaces it at submit time, the replica propagates it, and
the router maps it to one terminal ``adapter_load_failed`` stream event
— a failed load costs one request one clean error, never a wedged
stream and never a breaker strike (the replica is healthy).
"""
from __future__ import annotations

import threading
import time

import jax.numpy as jnp
import numpy as np

from paddle_tpu.distributed.resilience import faults
from paddle_tpu.lora import seam
from paddle_tpu.lora.adapter import DEFAULT_TARGETS, find_targets
from paddle_tpu.observability import events as obs_events
from paddle_tpu.observability import metrics as obs_metrics

__all__ = ["AdapterStore", "AdapterLoadError"]

faults.register(
    "serving.lora.swap_fail",
    "fail one adapter host->HBM swap-in at the AdapterStore: the request "
    "that needed it gets a typed AdapterLoadError (router surfaces ONE "
    "terminal adapter_load_failed event, no breaker strike, no failover) "
    "— other tenants' streams and the decode loop never notice")


class AdapterLoadError(RuntimeError):
    """Typed per-request adapter failure (unknown id / pool pinned full /
    swap-in failed): degrade the ONE request that asked, never the
    engine, the batch, or the stream transport."""

    def __init__(self, adapter_id: str, reason: str):
        super().__init__(f"adapter {adapter_id!r} failed to load: {reason}")
        self.adapter_id = str(adapter_id)
        self.reason = reason


import itertools as _itertools

_store_seq = _itertools.count()


def _register_store_metrics(store: "AdapterStore"):
    """Scrape-time collector (the engine-gauge idiom): residency, swap
    totals and latency mirror into the registry; the weakref owner
    unhooks a collected store automatically."""
    import weakref

    ref = weakref.ref(store)

    def collect(reg):
        s = ref()
        if s is None:
            return
        snap = s.residency()
        reg.gauge("lora_active_adapters",
                  "adapters resident in the HBM slot pool",
                  labels=("store",)).labels(store=s._metrics_id).set(
            float(len(snap["resident"])))
        reg.gauge("lora_registered_adapters",
                  "adapters registered in the host (cold) registry",
                  labels=("store",)).labels(store=s._metrics_id).set(
            float(snap["registered"]))
        reg.counter("lora_swap_total",
                    "adapter host->HBM swap-ins (pool loads + hot swaps)",
                    labels=("store",)).labels(
            store=s._metrics_id)._set_total(float(snap["swaps"]))
        reg.counter("lora_evictions_total",
                    "adapter slots evicted (LRU, refcount 0 only)",
                    labels=("store",)).labels(
            store=s._metrics_id)._set_total(float(snap["evictions"]))
        reg.gauge("lora_swap_ms",
                  "mean adapter swap-in latency (ms)",
                  labels=("store",)).labels(store=s._metrics_id).set(
            float(snap["swap_ms_mean"]))

    obs_metrics.registry().add_collector(collect, owner=store)


class AdapterStore:
    """Fixed-slot HBM adapter pool over a host-side registry for ONE
    base model's target projections (shapes discovered from the model —
    the same traversal `lora.attach` runs, so exported artifacts line up
    by construction)."""

    def __init__(self, model, *, rank: int, targets=DEFAULT_TARGETS,
                 slots: int = 0, dtype=None, block_rows: int = 8,
                 backend: str = "auto"):
        from paddle_tpu.core.flags import flag

        self.rank = int(rank)
        if self.rank <= 0:
            raise ValueError(f"adapter rank must be positive, got {rank}")
        self.num_slots = int(slots or flag("serving_adapter_slots"))
        if self.num_slots <= 0:
            raise ValueError(f"adapter pool needs >= 1 slot, got "
                             f"{self.num_slots}")
        self.targets = tuple(targets)
        self.block_rows = int(block_rows)
        self.backend = backend
        found = find_targets(model, self.targets)
        self._names = [n for n, _ in found]
        self._wids = [id(w) for _, w in found]
        self._dims = [(int(w.shape[0]), int(w.shape[1])) for _, w in found]
        if dtype is None:
            dt = np.dtype(found[0][1]._value.dtype)
        elif isinstance(dtype, str):
            from paddle_tpu.inference.artifact import np_dtype
            dt = np_dtype(dtype)
        else:
            dt = np.dtype(dtype)
        self.dtype = dt
        g, r = self.num_slots, self.rank
        self._a = [jnp.zeros((g, di, r), dt) for di, _ in self._dims]
        self._b = [jnp.zeros((g, r, do), dt) for _, do in self._dims]
        # host registry (cold tier): adapter id -> per-target (A, B*scale)
        self._host: dict[str, list] = {}
        self._slot_adapter: list[str | None] = [None] * g
        self._slot_by_id: dict[str, int] = {}
        self._refs = [0] * g
        self._tick = 0
        self._last_used = [0] * g
        self.swaps = 0
        self.swap_ms_total = 0.0
        self.evictions = 0
        self.load_failures = 0
        self._lock = threading.RLock()
        self._metrics_id = str(next(_store_seq))
        _register_store_metrics(self)

    # ---- registry (the cold tier) -----------------------------------------
    def register(self, adapter_id: str, source):
        """Register (or HOT-SWAP) an adapter: `source` is an artifact path
        or a `load_adapter()` blob. Validates rank + target coverage +
        factor shapes against the model-derived pool layout. If the id is
        already RESIDENT, its slot rows are rewritten in place — live
        requests pick the new weights up at their next dispatch (the
        pools ride as jit arguments, so no program ever recompiles)."""
        if isinstance(source, str):
            from paddle_tpu.lora.adapter import load_adapter
            source = load_adapter(source)
        meta, weights = source["adapter"], source["weights"]
        if int(meta["rank"]) != self.rank:
            raise ValueError(f"adapter {adapter_id!r}: rank {meta['rank']} "
                             f"!= store rank {self.rank}")
        missing = [n for n in self._names if n not in weights]
        if missing:
            raise ValueError(f"adapter {adapter_id!r} is missing factors "
                             f"for targets {missing}")
        scale = float(meta.get("alpha", self.rank)) / float(self.rank)
        rows = []
        for n, (di, do) in zip(self._names, self._dims):
            a, b = weights[n]
            a = np.asarray(a)
            b = np.asarray(b)
            if a.shape != (di, self.rank) or b.shape != (self.rank, do):
                raise ValueError(
                    f"adapter {adapter_id!r} target {n!r}: factor shapes "
                    f"{a.shape}/{b.shape} do not match the pool layout "
                    f"({(di, self.rank)}/{(self.rank, do)})")
            rows.append((a.astype(self.dtype),
                         (b.astype(np.float32) * scale).astype(self.dtype)))
        with self._lock:
            self._host[str(adapter_id)] = rows
            slot = self._slot_by_id.get(str(adapter_id))
            if slot is not None:          # hot swap under live traffic
                self._write_slot(slot, str(adapter_id), reason="hot_swap")

    def unregister(self, adapter_id: str):
        """Drop an adapter from the registry (and its slot when unpinned);
        a pinned adapter cannot be dropped mid-request."""
        aid = str(adapter_id)
        with self._lock:
            slot = self._slot_by_id.get(aid)
            if slot is not None:
                if self._refs[slot] > 0:
                    raise ValueError(f"adapter {aid!r} is pinned by "
                                     f"{self._refs[slot]} in-flight "
                                     f"request(s)")
                self._free_slot(slot)
            self._host.pop(aid, None)

    # ---- slot lifecycle (refcounted, LRU) ---------------------------------
    def acquire(self, adapter_id: str) -> int:
        """Pin `adapter_id` into a slot for one request (host->HBM swap-in
        on miss) and return the slot id — stable until the matching
        `release()`. Raises `AdapterLoadError` (typed, per-request) on an
        unknown id, a fully-pinned pool, or a chaos-failed swap."""
        aid = str(adapter_id)
        with self._lock:
            if aid not in self._host:
                self.load_failures += 1
                raise AdapterLoadError(aid, "not registered with the "
                                            "AdapterStore")
            slot = self._slot_by_id.get(aid)
            if slot is not None:
                self._refs[slot] += 1
                self._tick += 1
                self._last_used[slot] = self._tick
                return slot
            if faults.fire_check("serving.lora.swap_fail"):
                self.load_failures += 1
                raise AdapterLoadError(
                    aid, "host->HBM swap-in failed "
                         "(serving.lora.swap_fail)")
            slot = self._pick_slot()
            if slot is None:
                self.load_failures += 1
                raise AdapterLoadError(
                    aid, f"adapter pool exhausted: all {self.num_slots} "
                         f"slots pinned by in-flight requests")
            victim = self._slot_adapter[slot]
            if victim is not None:
                self._free_slot(slot)
                self.evictions += 1
                obs_events.emit("serving", "adapter_evict", severity="info",
                                adapter=victim, slot=slot, store=
                                self._metrics_id)
            self._write_slot(slot, aid, reason="load")
            self._refs[slot] = 1
            self._tick += 1
            self._last_used[slot] = self._tick
            return slot

    def release(self, adapter_id: str):
        aid = str(adapter_id)
        with self._lock:
            slot = self._slot_by_id.get(aid)
            if slot is not None and self._refs[slot] > 0:
                self._refs[slot] -= 1
                self._tick += 1
                self._last_used[slot] = self._tick

    def slot_of(self, adapter_id: str) -> int:
        """Resident slot of a PINNED adapter (the engine packs this into
        the per-row slot array each dispatch)."""
        with self._lock:
            slot = self._slot_by_id.get(str(adapter_id))
            if slot is None:
                raise KeyError(f"adapter {adapter_id!r} is not resident")
            return slot

    def _pick_slot(self):
        free = [i for i, a in enumerate(self._slot_adapter) if a is None]
        if free:
            return free[0]
        idle = [i for i in range(self.num_slots) if self._refs[i] == 0]
        if not idle:
            return None
        return min(idle, key=lambda i: self._last_used[i])

    def _free_slot(self, slot: int):
        aid = self._slot_adapter[slot]
        if aid is not None:
            self._slot_by_id.pop(aid, None)
        self._slot_adapter[slot] = None
        self._refs[slot] = 0

    def _write_slot(self, slot: int, adapter_id: str, reason: str):
        """The swap-in: write one adapter's factors into row `slot` of
        every target's pools (eager `.at[].set` — compiled scatter
        programs, the `_copy_page` idiom; the decode program itself never
        changes). Timed + journaled: this is the latency a cold tenant
        pays once, and the hot-swap latency the bench reports."""
        t0 = time.perf_counter()
        rows = self._host[adapter_id]
        for i, (a, b) in enumerate(rows):
            self._a[i] = self._a[i].at[slot].set(jnp.asarray(a))
            self._b[i] = self._b[i].at[slot].set(jnp.asarray(b))
        self._slot_adapter[slot] = adapter_id
        self._slot_by_id[adapter_id] = slot
        ms = (time.perf_counter() - t0) * 1e3
        self.swaps += 1
        self.swap_ms_total += ms
        obs_events.emit("serving", "adapter_swap", severity="info",
                        adapter=adapter_id, slot=slot, reason=reason,
                        ms=round(ms, 3), store=self._metrics_id)

    # ---- what the compiled programs consume --------------------------------
    def pools(self):
        """The (a_pools, b_pools) jit arguments for one dispatch — plain
        lists of fixed-shape arrays, snapshotted under the lock so a
        concurrent hot-swap can't tear one dispatch's view."""
        with self._lock:
            return list(self._a), list(self._b)

    def bind(self, a_pools, b_pools, slots):
        """Context manager used INSIDE traced programs: exposes the traced
        pool/slot arguments to `F.linear` via the seam."""
        pools = {wid: (a, b)
                 for wid, a, b in zip(self._wids, a_pools, b_pools)}
        return seam.serve_bind(seam.ServeBinding(
            pools, slots, self.num_slots,
            block_rows=self.block_rows, backend=self.backend))

    def validate_model(self, model):
        """The engine's construction check: the store must have been built
        against THIS model object (weight identity keys the seam)."""
        ids = {id(p) for p in model.parameters()}
        if not all(w in ids for w in self._wids):
            raise ValueError(
                "AdapterStore was built for a different model instance; "
                "construct it from the model the engine serves")

    # ---- observability -----------------------------------------------------
    @property
    def swap_ms_mean(self) -> float:
        return self.swap_ms_total / self.swaps if self.swaps else 0.0

    def residency(self) -> dict:
        """The /stats adapter snapshot: who is resident where, pinned by
        how many requests, plus swap/eviction totals."""
        with self._lock:
            return {
                "slots": self.num_slots,
                "rank": self.rank,
                "registered": len(self._host),
                "resident": [a for a in self._slot_adapter if a is not None],
                "refs": {a: self._refs[s]
                         for a, s in self._slot_by_id.items()},
                "swaps": self.swaps,
                "swap_ms_mean": round(self.swap_ms_mean, 3),
                "evictions": self.evictions,
                "load_failures": self.load_failures,
            }
