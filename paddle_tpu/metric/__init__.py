"""Metrics (reference: python/paddle/metric — Accuracy/Precision/Recall/Auc)."""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "accuracy"]


def accuracy(input, label, k=1):
    """Top-k accuracy (reference: python/paddle/metric/metrics.py accuracy)."""
    import jax.numpy as jnp

    logits = input._value
    lab = label._value.reshape(-1)
    topk_idx = jnp.argsort(logits, axis=-1)[..., ::-1][..., :k]
    correct = (topk_idx == lab[:, None]).any(axis=-1)
    return Tensor(jnp.mean(correct.astype(jnp.float32)))


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()


class Accuracy(Metric):
    def __init__(self, topk=(1,), name="acc"):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label):
        pred_np = np.asarray(pred._value if isinstance(pred, Tensor) else pred)
        lab = np.asarray(label._value if isinstance(label, Tensor) else label).reshape(-1)
        maxk = max(self.topk)
        idx = np.argsort(-pred_np, axis=-1)[:, :maxk]
        correct = idx == lab[:, None]
        return Tensor(np.asarray(correct, np.float32))

    def update(self, correct):
        c = np.asarray(correct._value if isinstance(correct, Tensor) else correct)
        for i, k in enumerate(self.topk):
            self.total[i] += c[:, :k].any(axis=-1).sum()
            self.count[i] += c.shape[0]
        return self.accumulate()

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds) > 0.5
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels).astype(bool)
        self.tp += int((p & l).sum())
        self.fp += int((p & ~l).sum())

    def accumulate(self):
        ap = self.tp + self.fp
        return self.tp / ap if ap else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds) > 0.5
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels).astype(bool)
        self.tp += int((p & l).sum())
        self.fn += int((~p & l).sum())

    def accumulate(self):
        al = self.tp + self.fn
        return self.tp / al if al else 0.0

    def name(self):
        return self._name
