"""Model zoo for the BASELINE workloads (configs 2-5)."""
from paddle_tpu.models.llama import (  # noqa: F401
    LlamaConfig, LlamaDecoderLayer, LlamaForCausalLM, LlamaModel,
    LlamaPretrainingCriterion, llama_7b_config, llama_tiny_config,
)
