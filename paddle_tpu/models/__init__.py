"""Model zoo for the BASELINE workloads (configs 2-5)."""
from paddle_tpu.models.llama import (  # noqa: F401
    LlamaConfig, LlamaDecoderLayer, LlamaForCausalLM, LlamaModel,
    LlamaPretrainingCriterion, llama_7b_config, llama_tiny_config,
)
from paddle_tpu.models.bert import (  # noqa: F401
    BertConfig, BertForMaskedLM, BertModel, bert_base_config, bert_tiny_config,
)
from paddle_tpu.models.gpt_moe import (  # noqa: F401
    GptMoeConfig, GptMoeForCausalLM, gpt_moe_tiny_config,
)
from paddle_tpu.models.gpt import (  # noqa: F401
    GptConfig, GptForCausalLM, gpt_tiny_config,
)
