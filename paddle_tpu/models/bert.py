"""BERT (BASELINE config[2]: BERT-base MLM with ZeRO-2 sharding).

Reference analog: the PaddleNLP BERT built on the reference's nn.TransformerEncoder
(python/paddle/nn/layer/transformer.py) — encoder stack + MLM head, trained
under GroupShardedStage2 (group_sharded_stage2.py:46). TPU-native: one compiled
step with optimizer state sharded over the dp/sharding axis (ZeRO).
"""
from __future__ import annotations

from dataclasses import dataclass

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

__all__ = ["BertConfig", "BertModel", "BertForMaskedLM", "bert_base_config", "bert_tiny_config"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12


def bert_base_config(**kw) -> BertConfig:
    return BertConfig(**kw)


def bert_tiny_config(**kw) -> BertConfig:
    cfg = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
               num_attention_heads=4, intermediate_size=128,
               max_position_embeddings=64, hidden_dropout_prob=0.0,
               attention_probs_dropout_prob=0.0)
    cfg.update(kw)
    return BertConfig(**cfg)


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        s = input_ids.shape[1]
        pos = paddle.arange(s, dtype="int64").unsqueeze(0)
        emb = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        layer = nn.TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads, config.intermediate_size,
            dropout=config.hidden_dropout_prob, activation="gelu",
            attn_dropout=config.attention_probs_dropout_prob,
            layer_norm_eps=config.layer_norm_eps,
        )
        self.encoder = nn.TransformerEncoder(layer, config.num_hidden_layers)
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        x = self.encoder(x, src_mask=attention_mask)
        return x


class BertForMaskedLM(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.decoder = nn.Linear(config.hidden_size, config.vocab_size)

    def forward(self, input_ids, labels=None, token_type_ids=None, attention_mask=None):
        hidden = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.layer_norm(F.gelu(self.transform(hidden)))
        logits = self.decoder(h)
        if labels is not None:
            return F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]),
                ignore_index=-100,
            )
        return logits
