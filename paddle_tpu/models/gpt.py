"""GPT-2-style dense decoder LM.

Reference analog: the GPT implementations driven through the reference's
fleet examples (and the incubate gpt modeling the MoE variant borrows from):
learned positional embeddings, pre-LN blocks, gelu MLP, tied LM head.
TPU-native: attention rides F.scaled_dot_product_attention (Pallas flash on
TPU); the block list decomposes for the compiled pipeline via
pipeline_layers (fleet PipelineLayer route).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor

__all__ = ["GptConfig", "GptForCausalLM", "gpt_tiny_config"]


@dataclass
class GptConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5


def gpt_tiny_config(**kw) -> GptConfig:
    cfg = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
               num_attention_heads=4, intermediate_size=128,
               max_position_embeddings=64)
    cfg.update(kw)
    return GptConfig(**cfg)


class GptAttention(nn.Layer):
    def __init__(self, config: GptConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        self.qkv = nn.Linear(h, 3 * h)
        self.proj = nn.Linear(h, h)

    def forward(self, x):
        B, S, H = x.shape
        packed = self.qkv(x)
        q, k, v = packed.chunk(3, axis=-1)

        def heads(t):
            return t.reshape([B, S, self.num_heads, self.head_dim])

        out = F.scaled_dot_product_attention(heads(q), heads(k), heads(v),
                                             is_causal=True)
        return self.proj(out.reshape([B, S, H]))


class GptBlock(nn.Layer):
    def __init__(self, config: GptConfig):
        super().__init__()
        h = config.hidden_size
        self.ln1 = nn.LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.attn = GptAttention(config)
        self.ln2 = nn.LayerNorm(h, epsilon=config.layer_norm_epsilon)
        self.fc1 = nn.Linear(h, config.intermediate_size)
        self.fc2 = nn.Linear(config.intermediate_size, h)
        self.drop = nn.Dropout(config.dropout)

    def forward(self, x):
        x = x + self.drop(self.attn(self.ln1(x)))
        x = x + self.drop(self.fc2(F.gelu(self.fc1(self.ln2(x)))))
        return x


class _GptEmbedding(nn.Layer):
    def __init__(self, config: GptConfig):
        super().__init__()
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)

    def forward(self, input_ids):
        S = input_ids.shape[-1]
        pos = paddle.to_tensor(np.arange(S, dtype=np.int64))
        return self.wte(input_ids) + self.wpe(pos)


class _GptHead(nn.Layer):
    def __init__(self, config: GptConfig):
        super().__init__()
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)
        self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                 bias_attr=False)

    def forward(self, x):
        return self.lm_head(self.ln_f(x))


class GptForCausalLM(nn.Layer):
    def __init__(self, config: GptConfig):
        super().__init__()
        self.config = config
        self.embed = _GptEmbedding(config)
        self.blocks = nn.LayerList([GptBlock(config)
                                    for _ in range(config.num_hidden_layers)])
        self.head = _GptHead(config)

    def forward(self, input_ids, labels=None):
        x = self.embed(input_ids)
        for blk in self.blocks:
            x = blk(x)
        logits = self.head(x)
        if labels is None:
            return logits
        V = self.config.vocab_size
        return F.cross_entropy(logits[:, :-1].reshape([-1, V]),
                               labels[:, 1:].reshape([-1]))

    @staticmethod
    def pipeline_layers(config: GptConfig, loss_fn=None):
        """LayerDesc list for the fleet PipelineLayer route."""
        from paddle_tpu.distributed.fleet.meta_parallel import LayerDesc

        descs = [LayerDesc(_GptEmbedding, config)]
        for _ in range(config.num_hidden_layers):
            descs.append(LayerDesc(GptBlock, config))
        descs.append(LayerDesc(_GptHead, config))
        return descs
