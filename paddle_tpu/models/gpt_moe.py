"""GPT-MoE (BASELINE config[5]: expert-parallel GPT via Fleet meta-parallel).

Reference analog: GPT decoder with the incubate MoE layer replacing the FFN
(incubate/distributed/models/moe/moe_layer.py:263; EP dispatch
global_scatter/global_gather). TPU-native: batched-expert FFN sharded over the
"ep" mesh axis; dispatch/combine einsums lower to ICI all-to-all under GSPMD.
"""
from __future__ import annotations

from dataclasses import dataclass

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.distributed.models.moe import MoELayer
from paddle_tpu.models.llama import LlamaAttention, LlamaConfig

__all__ = ["GptMoeConfig", "GptMoeForCausalLM", "gpt_moe_tiny_config"]


@dataclass
class GptMoeConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_hidden_layers: int = 12
    num_attention_heads: int = 16
    num_experts: int = 8
    expert_hidden_size: int = 4096
    top_k: int = 2
    max_position_embeddings: int = 2048
    moe_aux_loss_weight: float = 0.01
    dropout: float = 0.0
    # dispatch mode: None reads FLAGS_moe_dispatch; "dropless" runs the
    # sort-based ragged dispatch + Pallas grouped matmul (docs/moe.md)
    moe_dispatch: str | None = None
    # "token" (top-k gates) or "expert" (expert-choice routing)
    moe_router: str = "token"
    # >0 adds a dense shared-expert MLP per block, scheduled to overlap
    # the ep all_to_all in the dropless body
    shared_expert_hidden: int = 0


def gpt_moe_tiny_config(**kw) -> GptMoeConfig:
    cfg = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
               num_attention_heads=4, num_experts=4, expert_hidden_size=128,
               max_position_embeddings=64)
    cfg.update(kw)
    return GptMoeConfig(**cfg)


class GptMoeBlock(nn.Layer):
    def __init__(self, config: GptMoeConfig):
        super().__init__()
        # reuse the rope attention from llama (standard decoder attention)
        attn_cfg = LlamaConfig(
            vocab_size=config.vocab_size, hidden_size=config.hidden_size,
            intermediate_size=config.expert_hidden_size,
            num_hidden_layers=config.num_hidden_layers,
            num_attention_heads=config.num_attention_heads,
            num_key_value_heads=config.num_attention_heads,
            max_position_embeddings=config.max_position_embeddings,
        )
        self.ln1 = nn.LayerNorm(config.hidden_size)
        self.attn = LlamaAttention(attn_cfg)
        self.ln2 = nn.LayerNorm(config.hidden_size)
        self.moe = MoELayer(config.hidden_size, num_expert=config.num_experts,
                            d_hidden=config.expert_hidden_size,
                            top_k=config.top_k,
                            dispatch=config.moe_dispatch,
                            router=config.moe_router,
                            shared_expert_hidden=config.shared_expert_hidden)

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        x = x + self.moe(self.ln2(x))
        return x

    @property
    def l_aux(self):
        return self.moe.l_aux


class GptMoeForCausalLM(nn.Layer):
    def __init__(self, config: GptMoeConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.blocks = nn.LayerList([GptMoeBlock(config)
                                    for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size)
        self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)

    def forward(self, input_ids, labels=None):
        s = input_ids.shape[1]
        pos = paddle.arange(s, dtype="int64").unsqueeze(0)
        x = self.wte(input_ids) + self.wpe(pos)
        aux = None
        for blk in self.blocks:
            x = blk(x)
            aux = blk.l_aux if aux is None else aux + blk.l_aux
        logits = self.lm_head(self.ln_f(x))
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]))
            if aux is not None:
                loss = loss + self.config.moe_aux_loss_weight * aux.cast(loss.dtype)
            return loss
        return logits
