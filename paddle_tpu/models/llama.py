"""LLaMA-2 family (flagship model; BASELINE config[3] LLaMA-2-7B TP+PP).

Reference analog: the PaddleNLP LLaMA built from the reference's Fleet mpu
layers (ColumnParallelLinear mp_layers.py:334, RowParallelLinear :541,
VocabParallelEmbedding :47, ParallelCrossEntropy :742) + flash attention
(nn/functional/flash_attention.py:147) + RMSNorm + rotary embeddings.

TPU-native: attention runs the Pallas flash kernel (XLA fallback elsewhere);
TP shardings ride the "mp" mesh axis via the mpu layers' annotations; the
decoder-layer list is PipelineLayer-compatible for the "pp" axis; everything
trains in bfloat16 on the MXU.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import lru_cache

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor, apply_op
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "LlamaDecoderLayer",
           "LlamaPretrainingCriterion", "llama_tiny_config", "llama_7b_config"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_parallel_cross_entropy: bool = True
    dtype: str = "float32"
    # size of the ONE hoisted RoPE cos/sin buffer pair (absolute-position
    # indexed by the serving decode path); 0 = max_position_embeddings.
    # Raise it to serve contexts past the training length — any position at
    # or beyond it is a hard error, never a silent clamped-gather
    rope_max_position: int = 0
    # run the homogeneous decoder stack as ONE lax.scan over layer-stacked
    # params (O(1)-in-depth HLO/compile time); the global `scan_layers` flag
    # or a compiled step's scan packing can also turn this on
    scan_layers: bool = False


def llama_7b_config(**overrides) -> LlamaConfig:
    return LlamaConfig(**overrides)


def llama_tiny_config(**overrides) -> LlamaConfig:
    cfg = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
               num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
               max_position_embeddings=128)
    cfg.update(overrides)
    return LlamaConfig(**cfg)


def _rope_tables(head_dim: int, max_pos: int, theta: float):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_pos, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)  # [max_pos, head_dim/2]
    return jnp.cos(freqs), jnp.sin(freqs)


@lru_cache(maxsize=8)
def _shared_rope_tables(head_dim: int, max_pos: int, theta: float):
    """Process-wide RoPE cos/sin tables (fp32), shared by every attention
    layer of the same geometry. Layers no longer register their own buffer
    copies — LlamaModel holds ONE pair and passes it down; standalone layers
    (pipeline LayerDesc stages, GPT-MoE blocks) fall back to this cache.
    ensure_compile_time_eval: the first call may happen under a jit trace,
    and caching staged tracers would poison the cache for later traces."""
    with jax.ensure_compile_time_eval():
        return _rope_tables(head_dim, max_pos, theta)


def _rope_limit(config: LlamaConfig) -> int:
    return int(config.rope_max_position or config.max_position_embeddings)


def _check_positions(position_ids, limit: int):
    """Clear error when a position indexes past the hoisted RoPE tables.
    XLA gather CLAMPS out-of-range indices, so without this check a too-long
    context would silently reuse the last table row. Only HOST (numpy)
    values are checked — device arrays may be tracers, and syncing eager
    values per layer isn't worth it; traced decode steps are covered by
    the serving engine's constructor check (max_seq_len <= rope limit)
    and full-sequence forwards by the seq-len check below."""
    import numpy as _np

    if position_ids is None or not isinstance(position_ids, _np.ndarray):
        return
    mx = int(position_ids.max()) if position_ids.size else 0
    if mx >= limit:
        raise ValueError(
            f"position {mx} is past the hoisted RoPE table "
            f"(rope_max_position={limit}); raise "
            f"LlamaConfig.rope_max_position (or max_position_embeddings) "
            f"to serve longer contexts")


def _tag_residual(x):
    """`checkpoint_name` tag on the residual stream: the selective-remat
    policies (paddle_tpu.parallel.scan_layers) key on it, e.g.
    `offload_residuals` moves exactly these activations to pinned host
    memory. Numerically the identity."""
    return apply_op(lambda v: checkpoint_name(v, "residual"), x,
                    name="checkpoint_name")


def apply_rotary(q, k, cos, sin):
    """q,k: [B,S,H,D] arrays; cos/sin: [S, D/2] (shared row positions) or
    [B, S, D/2] (per-row positions, e.g. gathered by a packed batch's
    position ids). Interleaved-pair rotation."""
    c = cos[None, :, None, :] if cos.ndim == 2 else cos[:, :, None, :]
    s = sin[None, :, None, :] if sin.ndim == 2 else sin[:, :, None, :]

    def rot(x):
        x1, x2 = jnp.split(x, 2, axis=-1)
        return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)

    return rot(q), rot(k)


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.head_dim = config.hidden_size // config.num_attention_heads
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        h = config.hidden_size
        kv = self.num_kv_heads * self.head_dim
        self.q_proj = ColumnParallelLinear(h, h, has_bias=False, gather_output=False)
        self.k_proj = ColumnParallelLinear(h, kv, has_bias=False, gather_output=False)
        self.v_proj = ColumnParallelLinear(h, kv, has_bias=False, gather_output=False)
        self.o_proj = RowParallelLinear(h, h, has_bias=False, input_is_parallel=True)
        self._rope_geom = (self.head_dim, _rope_limit(config),
                           config.rope_theta)

    def forward(self, x, attn_mask=None, rope=None, segment_ids=None,
                position_ids=None):
        b, s, _ = x.shape
        q = self.q_proj(x).reshape([b, s, -1, self.head_dim])
        k = self.k_proj(x).reshape([b, s, -1, self.head_dim])
        v = self.v_proj(x).reshape([b, s, -1, self.head_dim])

        # packed-sequence metadata: explicit kwargs win; otherwise the
        # pipelined runtimes publish the current microbatch's ids in the
        # segment context (paddle_tpu.parallel.segments)
        if segment_ids is None and position_ids is None:
            from paddle_tpu.parallel.segments import current_segment_ctx

            ctx = current_segment_ctx()
            if ctx is not None:
                segment_ids, position_ids = ctx.segment_ids, ctx.position_ids
        segment_ids = (segment_ids._value if isinstance(segment_ids, Tensor)
                       else segment_ids)
        position_ids = (position_ids._value if isinstance(position_ids, Tensor)
                        else position_ids)

        # rope: (cos, sin) handed down by LlamaModel (one shared buffer pair
        # for the whole stack); standalone use falls back to the process-wide
        # cache — either way no per-layer buffer copies exist in the pytree
        if rope is None:
            rope = _shared_rope_tables(*self._rope_geom)
        cos, sin = (r._value if isinstance(r, Tensor) else r for r in rope)

        limit = self._rope_geom[1]
        _check_positions(position_ids, limit)

        def rope_fn(qv, kv_, c, sn):
            if position_ids is not None:
                # per-row positions (restarting at 0 per packed document):
                # index the shared tables by position id, [B, S, D/2]
                c = c[position_ids].astype(qv.dtype)
                sn = sn[position_ids].astype(qv.dtype)
            else:
                if s > limit:
                    raise ValueError(
                        f"sequence length {s} is past the hoisted RoPE "
                        f"table (rope_max_position={limit}); raise "
                        f"LlamaConfig.rope_max_position to run longer "
                        f"sequences")
                c = c[:s].astype(qv.dtype)
                sn = sn[:s].astype(qv.dtype)
            return apply_rotary(qv, kv_, c, sn)

        q, k = apply_op(rope_fn, q, k, cos, sin, name="rope", n_outputs=2)

        # GQA goes through natively: both the Pallas kernel and the XLA
        # fallback consume [B,S,Hkv,D] K/V without materializing repeats
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, is_causal=True,
                                             training=self.training,
                                             segment_ids=segment_ids)
        out = out.reshape([b, s, -1])
        return self.o_proj(out)

    def forward_decode(self, x, *, rope, cache, layer_idx, page_table,
                       context_lens, position_ids, ctx_pad=None,
                       write_mask=None, verify=False, segment_ids=None):
        """Serving forward over the paged KV cache. x: [B, T, H]; T == 1 is
        a decode step (paged ragged attention over the page table), T > 1
        is a page-writing prefill chunk (runs through the standard flash
        path over the gathered context) — unless `verify=True`, which runs
        the T-token SPECULATIVE VERIFY frame through the same paged kernel
        with per-query causal limits (query i at absolute position
        context_lens-1+i). `cache` is the raw
        {"k","v": [L, Hkv, P, page_size, D]} pool pair — plus
        {"k_scale","v_scale": [L, Hkv, P, page_size] float32} when the
        pools are quantized (int8/fp8): writes then quantize through the
        absmax observer and reads dequantize inside the paged kernel;
        this layer reads and functionally updates stack row `layer_idx`. position_ids
        [B, T] are ABSOLUTE positions (index the hoisted RoPE buffer);
        context_lens [B] counts valid cache tokens INCLUDING this chunk
        (for verify: committed context incl. the frame's rewrite token
        only — draft tokens are PROVISIONAL). `write_mask` [B, T] bool
        redirects masked entries' K/V writes to the reserved null page —
        how a verify frame keeps out-of-window draft slots (past a row's
        budget/context cap) from scribbling live cache.

        `segment_ids` [B, T] switches T > 1 into the PACKED MULTI-PROMPT
        prefill frame: several fresh prompts ride one frame, page_table is
        [n_segments + 1, pages] (one page chain per segment; the last row
        is all-null and backs pad/gap tokens), position_ids are
        SEGMENT-LOCAL, and attention runs the PR-5 segment-aware flash
        path over the frame itself. Returns (out, cache)."""
        from paddle_tpu.ops.pallas.paged_attention import paged_attention

        b, t, _ = x.shape
        packed = segment_ids is not None and t > 1 and not verify
        q = self.q_proj(x).reshape([b, t, -1, self.head_dim])
        k = self.k_proj(x).reshape([b, t, -1, self.head_dim])
        v = self.v_proj(x).reshape([b, t, -1, self.head_dim])
        cos, sin = (r._value if isinstance(r, Tensor) else r for r in rope)
        _check_positions(position_ids, self._rope_geom[1])
        qv, kv, vv = q._value, k._value, v._value
        c = cos[position_ids].astype(qv.dtype)
        sn = sin[position_ids].astype(qv.dtype)
        qv, kv = apply_rotary(qv, kv, c, sn)

        # write this chunk's K/V into its cache pages (functional scatter;
        # the engine donates the pools so XLA updates them in place)
        ck, cv = cache["k"], cache["v"]
        ps = ck.shape[3]
        if packed:
            # packed frame: a token's page CHAIN is its segment's row, its
            # column its segment-local position; pad/gap tokens carry the
            # all-null last row, so they spill to page 0 with no mask
            pidx = page_table[segment_ids, position_ids // ps]
        else:
            pidx = jnp.take_along_axis(page_table,
                                       position_ids // ps, axis=1)
        if write_mask is not None:
            # masked entries scatter into the null page (page 0): a
            # harmless spill target the allocator never hands out and the
            # kernel's skip predicate never reads as live context
            pidx = jnp.where(write_mask, pidx, 0)
        slot = position_ids % ps                                   # [B, T]
        # index tuple (int, :, [B,T], [B,T]): the advanced dims land in
        # FRONT position, so the updates keep their natural [B, T, Hkv, D]
        if "k_scale" in cache:
            # quantized pool: quantize-on-write through the SAME observer
            # math training quantization uses (per-slot-per-head absmax);
            # codes land in the int8/fp8 pool, scales in the f32 side pool
            from paddle_tpu.quantization import AbsmaxChannelWiseObserver
            qmax = 127.0 if ck.dtype == jnp.int8 else 448.0
            sck = AbsmaxChannelWiseObserver.kv_page_scales(kv, qmax=qmax)
            scv = AbsmaxChannelWiseObserver.kv_page_scales(vv, qmax=qmax)
            kq = kv.astype(jnp.float32) / sck[..., None]
            vq = vv.astype(jnp.float32) / scv[..., None]
            if ck.dtype == jnp.int8:
                kq = jnp.clip(jnp.round(kq), -127, 127)
                vq = jnp.clip(jnp.round(vq), -127, 127)
            ck = ck.at[layer_idx, :, pidx, slot].set(kq.astype(ck.dtype))
            cv = cv.at[layer_idx, :, pidx, slot].set(vq.astype(cv.dtype))
            cks = cache["k_scale"].at[layer_idx, :, pidx, slot].set(sck)
            cvs = cache["v_scale"].at[layer_idx, :, pidx, slot].set(scv)
            cache = {"k": ck, "v": cv, "k_scale": cks, "v_scale": cvs}
            k_sc, v_sc = cks[layer_idx], cvs[layer_idx]
        else:
            ck = ck.at[layer_idx, :, pidx, slot].set(kv.astype(ck.dtype))
            cv = cv.at[layer_idx, :, pidx, slot].set(vv.astype(cv.dtype))
            cache = {"k": ck, "v": cv}
            k_sc = v_sc = None

        if t == 1:
            out = paged_attention(qv[:, 0], ck[layer_idx], cv[layer_idx],
                                  page_table, context_lens,
                                  k_scales=k_sc, v_scales=v_sc)[:, None]
        elif verify:
            # the [B, T, Hq, D] query frame rides the SAME scalar-prefetch
            # page gather as plain decode; per-query causal limits live in
            # the kernel (query i sees keys < context_lens + i, which
            # includes the draft K/V scattered just above)
            out = paged_attention(qv, ck[layer_idx], cv[layer_idx],
                                  page_table, context_lens,
                                  k_scales=k_sc, v_scales=v_sc)
        elif packed:
            # packed multi-prompt prefill: every segment is a FRESH prompt
            # whose full K/V sits in this very frame, so attention runs the
            # segment-aware flash path over the frame itself — no page
            # gather. The in-frame K/V first round-trips through the cache
            # dtype (identity when the pool stores the model dtype, the
            # chunked gather's dequant when quantized), so packed pages AND
            # outputs stay bit-equal to sequential chunked prefill. Frame
            # causality == per-segment causality because each segment's
            # tokens are contiguous and ordered; pads only see the null
            # segment.
            if k_sc is not None:
                k_in = (kq.astype(ck.dtype).astype(qv.dtype)
                        * sck[..., None].astype(qv.dtype))
                v_in = (vq.astype(cv.dtype).astype(qv.dtype)
                        * scv[..., None].astype(qv.dtype))
            else:
                k_in = kv.astype(ck.dtype).astype(qv.dtype)
                v_in = vv.astype(cv.dtype).astype(qv.dtype)
            out = F.scaled_dot_product_attention(
                qv, k_in, v_in, is_causal=True, training=False,
                segment_ids=segment_ids)
            out = out._value if isinstance(out, Tensor) else out
        else:
            # chunked prefill: gather the full context (pages cover the
            # chunk itself too — just scattered above) and run the SAME
            # flash kernel training uses, with the chunk's queries placed
            # at their absolute rows of a [B, ctx_pad] frame so the causal
            # mask sees true positions; rows past context are padding
            # whose outputs are dropped by the take_along_axis below
            if ctx_pad is None:
                raise ValueError("prefill chunks need ctx_pad (the padded "
                                 "context bucket the engine compiled for)")
            pos_full = jnp.arange(ctx_pad, dtype=jnp.int32)
            pidx_f = page_table[:, pos_full // ps]                 # [B, S]
            slot_f = jnp.broadcast_to(pos_full % ps, (b, ctx_pad))
            k_full = jnp.moveaxis(ck[layer_idx][:, pidx_f, slot_f],
                                  0, 2).astype(qv.dtype)           # [B,S,Hkv,D]
            v_full = jnp.moveaxis(cv[layer_idx][:, pidx_f, slot_f],
                                  0, 2).astype(qv.dtype)
            if k_sc is not None:
                # dequant the gathered context (prefill runs the flash
                # path over bf16 activations; the pool stays quantized)
                ksf = jnp.moveaxis(k_sc[:, pidx_f, slot_f], 0, 2)  # [B,S,Hkv]
                vsf = jnp.moveaxis(v_sc[:, pidx_f, slot_f], 0, 2)
                k_full = k_full * ksf[..., None].astype(qv.dtype)
                v_full = v_full * vsf[..., None].astype(qv.dtype)
            q_full = jnp.zeros((b, ctx_pad) + qv.shape[2:], qv.dtype)
            bidx = jnp.arange(b)[:, None]
            q_full = q_full.at[bidx, position_ids].set(qv)
            out_full = F.scaled_dot_product_attention(
                q_full, k_full, v_full, is_causal=True, training=False)
            out = jnp.take_along_axis(
                out_full._value if isinstance(out_full, Tensor) else out_full,
                position_ids[:, :, None, None], axis=1)
        out = Tensor(out) if not isinstance(out, Tensor) else out
        out = out.reshape([b, t, -1])
        return self.o_proj(out), cache


def _raw(a):
    """Unwrap Tensor -> jnp value (functional_call wraps top-level array
    kwargs; the decode metadata must reach the kernels raw)."""
    if a is None:
        return None
    return a._value if isinstance(a, Tensor) else jnp.asarray(a)


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        self.gate_proj = ColumnParallelLinear(h, m, has_bias=False, gather_output=False)
        self.up_proj = ColumnParallelLinear(h, m, has_bias=False, gather_output=False)
        self.down_proj = RowParallelLinear(m, h, has_bias=False, input_is_parallel=True)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, attn_mask=None, rope=None, segment_ids=None,
                position_ids=None):
        x = _tag_residual(x + self.self_attn(self.input_layernorm(x),
                                             attn_mask, rope=rope,
                                             segment_ids=segment_ids,
                                             position_ids=position_ids))
        x = _tag_residual(x + self.mlp(self.post_attention_layernorm(x)))
        return x

    def forward_decode(self, x, *, rope, cache, layer_idx, page_table,
                       context_lens, position_ids, ctx_pad=None,
                       write_mask=None, verify=False, segment_ids=None):
        attn_out, cache = self.self_attn.forward_decode(
            self.input_layernorm(x), rope=rope, cache=cache,
            layer_idx=layer_idx, page_table=page_table,
            context_lens=context_lens, position_ids=position_ids,
            ctx_pad=ctx_pad, write_mask=write_mask, verify=verify,
            segment_ids=segment_ids)
        x = x + attn_out
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x, cache


class LlamaModel(nn.Layer):
    # cooperation protocol (paddle_tpu.parallel.scan_layers): compiled steps
    # deliver the per-layer remat policy / stacked scan params via
    # layer_execution() instead of wrapping the whole loss in jax.checkpoint
    layer_remat_capable = True

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList([LlamaDecoderLayer(config)
                                    for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        # ONE shared RoPE table pair for the whole stack (previously every
        # attention layer registered its own [max_pos, head_dim/2] copies);
        # sized by rope_max_position so the serving decode path can index it
        # at absolute positions past the training max_position_embeddings
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = _rope_tables(head_dim, _rope_limit(config),
                                config.rope_theta)
        self.register_buffer("rope_cos", cos, persistable=False)
        self.register_buffer("rope_sin", sin, persistable=False)

    def scan_group(self):
        """The homogeneous decoder stack, for scan-over-layers packing."""
        return list(self.layers)

    def forward(self, input_ids, attn_mask=None, segment_ids=None,
                position_ids=None):
        x = self.embed_tokens(input_ids)
        x = self._run_layers(x, attn_mask, segment_ids, position_ids)
        return self.norm(x)

    def decode_forward(self, input_ids, cache, page_table, context_lens,
                       position_ids, ctx_pad=None, write_mask=None,
                       verify=False, segment_ids=None):
        """Serving forward over the paged KV cache (decode step when
        input_ids is [B, 1], page-writing prefill chunk when [B, T>1],
        speculative verify frame when [B, T>1] with verify=True, packed
        multi-prompt prefill frame when [B, T>1] with segment_ids).
        `cache` = raw {"k","v": [L, Hkv, P, page_size, D]} pools; returns
        (hidden, updated cache). The layer loop is an unrolled Python loop
        — decode programs are tiny next to training HLO, and every layer
        scatters into its own stack row of the donated pools."""
        page_table = _raw(page_table).astype(jnp.int32)
        context_lens = _raw(context_lens).astype(jnp.int32)
        position_ids = _raw(position_ids).astype(jnp.int32)
        write_mask = _raw(write_mask)
        segment_ids = (_raw(segment_ids).astype(jnp.int32)
                       if segment_ids is not None else None)
        x = self.embed_tokens(input_ids)
        rope = (self.rope_cos._value, self.rope_sin._value)
        for i, layer in enumerate(self.layers):
            x, cache = layer.forward_decode(
                x, rope=rope, cache=cache, layer_idx=i,
                page_table=page_table, context_lens=context_lens,
                position_ids=position_ids, ctx_pad=ctx_pad,
                write_mask=write_mask, verify=verify,
                segment_ids=segment_ids)
        return self.norm(x), cache

    def _run_layers(self, x, attn_mask, segment_ids=None, position_ids=None):
        """Apply the decoder stack: unrolled python loop, or ONE lax.scan
        over layer-stacked params, with the active selective-remat policy
        applied PER LAYER (embed/norm/head never sit in a remat region)."""
        from paddle_tpu.core.flags import flag
        from paddle_tpu.parallel.scan_layers import (
            current_layer_ctx, scan_layer_stack, stack_layer_vals,
            unrolled_layer_call)

        rope = (self.rope_cos._value, self.rope_sin._value)
        layers = list(self.layers)
        ctx = current_layer_ctx()
        policy = ctx.policy if ctx is not None else flag("remat_policy")
        stacked = ctx.stacked if ctx is not None else None
        # packed-batch metadata rides the layer kwargs (layer-invariant, so
        # the scan path broadcasts ONE copy to every scanned layer)
        seg = (segment_ids._value if isinstance(segment_ids, Tensor)
               else segment_ids)
        pos = (position_ids._value if isinstance(position_ids, Tensor)
               else position_ids)
        kwargs = {"attn_mask": attn_mask, "rope": rope,
                  "segment_ids": seg, "position_ids": pos}
        use_scan = stacked is not None or (
            len(layers) > 1 and (self.config.scan_layers
                                 or flag("scan_layers")))
        if not use_scan:
            if policy == "none":
                for layer in layers:
                    x = layer(x, attn_mask, rope=rope, segment_ids=seg,
                              position_ids=pos)
                return x
            for layer in layers:
                x = unrolled_layer_call(layer, x, kwargs=kwargs,
                                        policy=policy)
            return x
        template = layers[0]
        if stacked is not None:
            # stacked [L, ...] arrays arrive from the compiled step's packing
            # (jit inputs — the program never stacks or slices per layer).
            # shard_info: ZeRO-3 — they persist reduce-scattered and the
            # scan gathers layer k+1's weights while layer k computes
            return Tensor(scan_layer_stack(
                template, stacked, x._value, kwargs=kwargs, policy=policy,
                shard_info=getattr(ctx, "shard_info", None)))
        # stack the per-layer parameter values in-program (eager / unpacked
        # traced mode); the tape records ONE scan op with per-param grads
        n_per = len(template.parameters())
        n_layers = len(layers)
        flat = [p for layer in layers for p in layer.parameters()]

        def scan_all(hv, *leafs):
            svals = stack_layer_vals(
                [leafs[l * n_per:(l + 1) * n_per] for l in range(n_layers)])
            return scan_layer_stack(template, svals, hv, kwargs=kwargs,
                                    policy=policy)

        return apply_op(scan_all, x, *flat, name="scan_layers")


class LlamaPretrainingCriterion(nn.Layer):
    """Causal-LM loss; TP-aware CE over the sharded vocab (reference
    ParallelCrossEntropy mp_layers.py:742)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.parallel_ce = ParallelCrossEntropy() if config.use_parallel_cross_entropy else None

    def forward(self, logits, labels):
        if self.parallel_ce is not None:
            loss = self.parallel_ce(logits, labels)
            return loss.mean()
        return F.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]))

    def forward_fused(self, hidden, lm_head, labels):
        """Joint head-projection + CE through the chunked fused kernel
        (paddle_tpu.ops.pallas.fused_ce): `CE(hidden @ W_head, labels)`
        without ever materializing the [tokens, vocab] logits, preserving
        this criterion's exact reduction semantics — per-token parallel CE
        then mean over ALL tokens when use_parallel_cross_entropy, else
        F.cross_entropy's mean over non-ignored tokens."""
        if self.parallel_ce is not None:
            per_tok = F.fused_linear_cross_entropy(
                hidden, lm_head.weight, labels, bias=lm_head.bias,
                ignore_index=self.parallel_ce.ignore_index, reduction="none")
            return per_tok.mean()
        return F.fused_linear_cross_entropy(
            hidden, lm_head.weight, labels, bias=lm_head.bias,
            reduction="mean")


class LlamaForCausalLM(nn.Layer):
    layer_remat_capable = True

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size,
                                            has_bias=False, gather_output=False)
        self.criterion = LlamaPretrainingCriterion(config)

    def scan_group(self):
        return self.llama.scan_group()

    def forward(self, input_ids, labels=None, attn_mask=None,
                segment_ids=None, position_ids=None):
        from paddle_tpu.amp.fp8 import head_scope

        hidden = self.llama(input_ids, attn_mask, segment_ids=segment_ids,
                            position_ids=position_ids)
        if labels is not None:
            from paddle_tpu.core.flags import flag

            with head_scope():
                # head_scope: under fp8_policy='matmuls' the head matmul
                # stays bf16; 'matmuls+head' quantizes it too (the fused-CE
                # kernel keeps its softmax statistics fp32 either way)
                if flag("use_fused_head_loss"):
                    # head projection + CE in one chunked custom-vjp: the
                    # [tokens, vocab] logits never exist (escape hatch:
                    # use_fused_head_loss=False restores the unfused path)
                    return self.criterion.forward_fused(hidden, self.lm_head,
                                                        labels)
                return self.criterion(self.lm_head(hidden), labels)
        with head_scope():
            return self.lm_head(hidden)

    def decode_forward(self, input_ids, cache, page_table, context_lens,
                       position_ids, ctx_pad=None, write_mask=None,
                       verify=False, segment_ids=None):
        """Serving decode/prefill/verify entry: (logits [B, T, vocab],
        cache)."""
        hidden, cache = self.llama.decode_forward(
            input_ids, cache, page_table, context_lens, position_ids,
            ctx_pad=ctx_pad, write_mask=write_mask, verify=verify,
            segment_ids=segment_ids)
        return self.lm_head(hidden), cache

    # ---- pipeline-parallel factory ----------------------------------------
    @staticmethod
    def pipeline_layers(config: LlamaConfig, loss_fn=None):
        """LayerDesc list for PipelineLayer (reference pp_layers.py usage)."""
        from paddle_tpu.distributed.fleet.meta_parallel import LayerDesc

        descs = [LayerDesc(_EmbeddingStage, config)]
        for _ in range(config.num_hidden_layers):
            descs.append(LayerDesc(LlamaDecoderLayer, config))
        descs.append(LayerDesc(_HeadStage, config))
        return descs


class _EmbeddingStage(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size, config.hidden_size)

    def forward(self, input_ids):
        return self.embed_tokens(input_ids)


class _HeadStage(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size,
                                            has_bias=False, gather_output=False)

    def forward_features(self, x):
        """Pre-projection hidden — the fused head+loss protocol
        (paddle_tpu.parallel.fused_head): forward == lm_head(forward_features)."""
        return self.norm(x)

    def forward(self, x):
        from paddle_tpu.amp.fp8 import head_scope

        with head_scope():
            return self.lm_head(self.forward_features(x))
