"""paddle_tpu.nn — layers + functional (reference: python/paddle/nn)."""
from paddle_tpu.nn.layer.layers import (  # noqa: F401
    Identity, Layer, LayerDict, LayerList, Parameter, ParameterList, Sequential,
)
from paddle_tpu.nn.layer.common import (  # noqa: F401
    AlphaDropout, Bilinear, ChannelShuffle, CosineSimilarity, Dropout,
    Dropout2D, Dropout3D, Embedding, Flatten, Fold, Linear, LpPool2D,
    MaxUnPool2D, Pad1D, Pad2D, PairwiseDistance, PixelShuffle, PixelUnshuffle,
    Softmax2D, Unflatten, Unfold, Upsample, UpsamplingBilinear2D,
    UpsamplingNearest2D, ZeroPad2D,
)
from paddle_tpu.nn.layer.conv import (  # noqa: F401
    Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D, Conv3DTranspose,
)
from paddle_tpu.nn.layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm, InstanceNorm2D,
    LayerNorm, LocalResponseNorm, RMSNorm, SyncBatchNorm,
    InstanceNorm1D, InstanceNorm3D, SpectralNorm,
)
from paddle_tpu.nn.layer.pooling import (  # noqa: F401
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool2D, AvgPool1D, AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D,
    MaxPool3D,
)
from paddle_tpu.nn.layer.activation import (  # noqa: F401
    CELU, ELU, GELU, GLU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh,
    LeakyReLU, LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6, SELU,
    Sigmoid, Silu, Softmax, Softplus, Softshrink, Softsign, Swish, Tanh,
    Tanhshrink, ThresholdedReLU,
)
from paddle_tpu.nn.layer.loss import (  # noqa: F401
    AdaptiveLogSoftmaxWithLoss, BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss, CrossEntropyLoss,
    CTCLoss, GaussianNLLLoss, HingeEmbeddingLoss, HuberLoss, KLDivLoss,
    L1Loss, MarginRankingLoss, MSELoss, MultiLabelSoftMarginLoss, NLLLoss,
    PoissonNLLLoss, RNNTLoss, SmoothL1Loss, SoftMarginLoss, TripletMarginLoss,
    TripletMarginWithDistanceLoss,
)
from paddle_tpu.nn.layer.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder, TransformerDecoderLayer,
    TransformerEncoder, TransformerEncoderLayer,
)
from paddle_tpu.nn.layer.rnn import (  # noqa: F401
    GRU, GRUCell, LSTM, LSTMCell, RNN, SimpleRNN, SimpleRNNCell,
    BiRNN, RNNCellBase,
)

from paddle_tpu.nn import functional  # noqa: F401
from paddle_tpu.nn import initializer  # noqa: F401
from paddle_tpu.nn.initializer import ParamAttr  # noqa: F401
from paddle_tpu.nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from paddle_tpu.nn.utils_ import parameters_to_vector, vector_to_parameters  # noqa: F401
from paddle_tpu.nn import utils  # noqa: F401
