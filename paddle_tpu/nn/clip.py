"""Gradient clipping (reference: python/paddle/nn/clip.py). The hybrid-parallel
variant that reduces the global norm across mesh axes lives in
paddle_tpu.distributed.fleet (HybridParallelClipGrad analog)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm", "clip_grad_norm_"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            n = jnp.sqrt(jnp.sum(jnp.square(g._value)))
            factor = jnp.where(n > self.clip_norm, self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append((p, Tensor(g._value * factor)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _global_norm(self, grads):
        sq = sum(jnp.sum(jnp.square(g._value.astype(jnp.float32))) for g in grads)
        return jnp.sqrt(sq)

    def __call__(self, params_grads):
        grads = [g for _, g in params_grads if g is not None]
        if not grads:
            return params_grads
        gn = self._global_norm(grads)
        factor = jnp.where(gn > self.clip_norm, self.clip_norm / jnp.maximum(gn, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor((g._value.astype(jnp.float32) * factor).astype(g._value.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    grads = [p.grad for p in params if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g._value), norm_type)) for g in grads), 1.0 / norm_type
        )
    factor = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        if p.grad is not None:
            p.grad._set_value(p.grad._value * factor)
    return Tensor(total)
