"""Functional NN ops (reference: python/paddle/nn/functional).

Every function lowers to XLA-friendly jax ops: convs via lax.conv_general_dilated
(MXU), attention via Pallas flash attention when available (reference analog:
nn/functional/flash_attention.py:147 wrapping third_party/flashattn), with an
XLA softmax fallback. NCHW layout is the API default (paddle convention); XLA
re-lays-out internally for the TPU.
"""
from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dtype import to_jax_dtype
from paddle_tpu.core.flags import flag
from paddle_tpu.core.tensor import Tensor, apply_op
from paddle_tpu.lora import seam as _lora_seam
from paddle_tpu.ops.random_state import default_generator

__all__ = [
    # activations
    "relu", "relu6", "gelu", "sigmoid", "silu", "swish", "tanh", "softmax",
    "log_softmax", "leaky_relu", "elu", "selu", "celu", "hardshrink",
    "hardsigmoid", "hardswish", "hardtanh", "mish", "softplus", "softshrink",
    "softsign", "tanhshrink", "thresholded_relu", "log_sigmoid", "glu",
    "prelu", "rrelu", "maxout",
    # linear / embedding
    "linear", "embedding", "one_hot", "bilinear",
    # conv / pool
    "conv1d", "conv2d", "conv3d", "conv2d_transpose", "max_pool1d",
    "max_pool2d", "avg_pool1d", "avg_pool2d", "adaptive_avg_pool1d",
    "adaptive_avg_pool2d", "adaptive_max_pool2d", "unfold", "interpolate",
    "upsample", "pixel_shuffle",
    # norm
    "layer_norm", "batch_norm", "instance_norm", "group_norm", "rms_norm",
    "local_response_norm", "normalize",
    # dropout
    "dropout", "dropout2d", "alpha_dropout",
    # losses
    "cross_entropy", "parallel_cross_entropy", "fused_linear_cross_entropy",
    "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "cosine_similarity",
    "ctc_loss", "hinge_embedding_loss", "cosine_embedding_loss", "triplet_margin_loss",
    "label_smooth", "square_error_cost", "sigmoid_focal_loss",
    # attention
    "scaled_dot_product_attention", "flash_attention", "sequence_mask", "pad",
    "temperature_scaled_softmax",
]

from paddle_tpu.ops.manipulation import pad  # noqa: F401  (re-export)


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

def _act(fn, name):
    def op(x, *args, **kwargs):
        return apply_op(lambda v: fn(v, *args, **kwargs), _t(x), name=name)

    op.__name__ = name
    return op


relu = _act(jax.nn.relu, "relu")
relu6 = _act(jax.nn.relu6, "relu6")
sigmoid = _act(jax.nn.sigmoid, "sigmoid")
silu = _act(jax.nn.silu, "silu")
swish = _act(jax.nn.silu, "swish")
tanh = _act(jnp.tanh, "tanh")
softplus = _act(jax.nn.softplus, "softplus")
softsign = _act(jax.nn.soft_sign, "softsign")
log_sigmoid = _act(jax.nn.log_sigmoid, "log_sigmoid")
mish = _act(jax.nn.mish, "mish")


def gelu(x, approximate=False):
    return apply_op(lambda v: jax.nn.gelu(v, approximate=approximate), _t(x), name="gelu")


def softmax(x, axis=-1, dtype=None):
    d = to_jax_dtype(dtype)

    def f(v):
        if d is not None:
            v = v.astype(d)
        return jax.nn.softmax(v, axis=axis)

    return apply_op(f, _t(x), name="softmax")


def temperature_scaled_softmax(x, temperature=1.0, axis=-1):
    return apply_op(lambda v: jax.nn.softmax(v / temperature, axis=axis), _t(x), name="softmax")


def log_softmax(x, axis=-1, dtype=None):
    d = to_jax_dtype(dtype)

    def f(v):
        if d is not None:
            v = v.astype(d)
        return jax.nn.log_softmax(v, axis=axis)

    return apply_op(f, _t(x), name="log_softmax")


def leaky_relu(x, negative_slope=0.01):
    return apply_op(lambda v: jax.nn.leaky_relu(v, negative_slope), _t(x), name="leaky_relu")


def elu(x, alpha=1.0):
    return apply_op(lambda v: jax.nn.elu(v, alpha), _t(x), name="elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return apply_op(
        lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), _t(x), name="selu"
    )


def celu(x, alpha=1.0):
    return apply_op(lambda v: jax.nn.celu(v, alpha), _t(x), name="celu")


def hardshrink(x, threshold=0.5):
    return apply_op(
        lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), _t(x), name="hardshrink"
    )


def hardsigmoid(x, slope=1.0 / 6, offset=0.5):
    return apply_op(
        lambda v: jnp.clip(slope * v + offset, 0.0, 1.0), _t(x), name="hardsigmoid"
    )


def hardswish(x):
    return apply_op(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, _t(x), name="hardswish")


def hardtanh(x, min=-1.0, max=1.0):
    return apply_op(lambda v: jnp.clip(v, min, max), _t(x), name="hardtanh")


def softshrink(x, threshold=0.5):
    return apply_op(
        lambda v: jnp.where(v > threshold, v - threshold, jnp.where(v < -threshold, v + threshold, 0.0)),
        _t(x), name="softshrink",
    )


def tanhshrink(x):
    return apply_op(lambda v: v - jnp.tanh(v), _t(x), name="tanhshrink")


def thresholded_relu(x, threshold=1.0):
    return apply_op(lambda v: jnp.where(v > threshold, v, 0.0), _t(x), name="thresholded_relu")


def glu(x, axis=-1):
    def f(v):
        a, b = jnp.split(v, 2, axis=axis)
        return a * jax.nn.sigmoid(b)

    return apply_op(f, _t(x), name="glu")


def prelu(x, weight):
    return apply_op(
        lambda v, w: jnp.where(v > 0, v, _reshape_prelu(w, v) * v), _t(x), _t(weight), name="prelu"
    )


def _reshape_prelu(w, v):
    if w.size == 1:
        return w.reshape(())
    shape = [1] * v.ndim
    shape[1] = w.size
    return w.reshape(shape)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True):
    if not training:
        return apply_op(lambda v: jnp.where(v >= 0, v, v * (lower + upper) / 2), _t(x), name="rrelu")
    key = default_generator.next_key()

    def f(v):
        slope = jax.random.uniform(key, v.shape, v.dtype, lower, upper)
        return jnp.where(v >= 0, v, v * slope)

    return apply_op(f, _t(x), name="rrelu")


def maxout(x, groups, axis=1):
    def f(v):
        shape = list(v.shape)
        c = shape[axis]
        shape[axis] = c // groups
        shape.insert(axis + 1, groups)
        return jnp.max(v.reshape(shape), axis=axis + 1)

    return apply_op(f, _t(x), name="maxout")


# ---------------------------------------------------------------------------
# linear / embedding
# ---------------------------------------------------------------------------

# linear's collaborators, bound once on first call instead of re-imported
# per projection per decode step (this seam is the per-token hot path)
_fp8 = None
_prec = None


def _bind_linear_deps():
    global _fp8, _prec
    from paddle_tpu.amp import fp8 as fp8_mod
    from paddle_tpu.ops.linalg import _prec as prec_fn

    _fp8 = fp8_mod
    _prec = prec_fn


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b; W is [in, out] (paddle convention, nn/functional/common.py).

    Under an active fp8 session (`CompiledTrainStep(fp8_policy=...)`, the
    pipelined runtimes, or `amp.fp8_autocast`) the matmul runs through
    float8_e4m3 with e5m2 gradients — the hot-path seam the fp8 policy
    hooks (paddle_tpu.amp.fp8).

    This is also the LoRA dispatch seam (paddle_tpu.lora.seam): when this
    weight has attached train-mode A/B factors, or a serving AdapterStore
    binding is active inside the traced program, the rank-r delta is added
    here — every projection layer routes through this one function, so no
    model rewrite is needed to adapt it."""
    if _fp8 is None:
        _bind_linear_deps()
    xt, wt = _t(x), _t(weight)
    if _fp8.linear_fp8_enabled(xt._value, wt._value):
        return _fp8.fp8_linear(xt, wt, None if bias is None else _t(bias))
    if _lora_seam.active():
        sb = _lora_seam.serve_binding()
        if sb is not None:
            pool = sb.pools.get(id(weight))
            if pool is not None:
                a_pool, b_pool = pool

                def f_serve(v, w, *rest):
                    y = jnp.matmul(v, w, precision=_prec())
                    d = _lora_seam.serve_delta(v, a_pool, b_pool, sb)
                    y = y + d.astype(y.dtype)
                    return y + rest[0] if rest else y

                args = (xt, wt) if bias is None else (xt, wt, _t(bias))
                return apply_op(f_serve, *args, name="linear")
        entry = _lora_seam.train_lookup(id(weight))
        if entry is not None:
            s = entry.scale

            def f_train(v, w, a, b2, *rest):
                y = jnp.matmul(v, w, precision=_prec())
                d = jnp.matmul(jnp.matmul(v, a, precision=_prec()), b2,
                               precision=_prec())
                y = y + (s * d).astype(y.dtype)
                return y + rest[0] if rest else y

            args = (xt, wt, _t(entry.A), _t(entry.B))
            if bias is not None:
                args = args + (_t(bias),)
            return apply_op(f_train, *args, name="linear")
    if bias is None:
        return apply_op(lambda v, w: jnp.matmul(v, w, precision=_prec()), xt, wt, name="linear")
    return apply_op(
        lambda v, w, b: jnp.matmul(v, w, precision=_prec()) + b,
        xt, wt, _t(bias), name="linear",
    )


def embedding(x, weight, padding_idx=None, sparse=False):
    def f(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return apply_op(f, _t(x), _t(weight), name="embedding")


def one_hot(x, num_classes):
    from paddle_tpu.ops.creation import one_hot as _oh

    return _oh(x, num_classes)


def bilinear(x1, x2, weight, bias=None):
    def f(a, b, w):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        return out

    out = apply_op(f, _t(x1), _t(x2), _t(weight), name="bilinear")
    if bias is not None:
        out = out + _t(bias)
    return out


# ---------------------------------------------------------------------------
# convolution / pooling
# ---------------------------------------------------------------------------

def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, nd, data_format):
    strides = _pair(stride, nd)
    dils = _pair(dilation, nd)
    if isinstance(padding, str):
        pad_cfg = padding.upper()  # SAME / VALID
    else:
        p = _pair(padding, nd) if not (isinstance(padding, (list, tuple)) and isinstance(padding[0], (list, tuple))) else padding
        pad_cfg = [(int(pi), int(pi)) for pi in p] if not isinstance(p[0], tuple) else p
    chan = "NCHW"[: 2 + nd] if nd == 2 else ("NCH" if nd == 1 else "NCDHW")
    if nd == 1:
        dn = jax.lax.conv_dimension_numbers(x._value.shape, weight._value.shape, ("NCH", "OIH", "NCH"))
    elif nd == 2:
        dn = jax.lax.conv_dimension_numbers(x._value.shape, weight._value.shape, ("NCHW", "OIHW", "NCHW"))
    else:
        dn = jax.lax.conv_dimension_numbers(x._value.shape, weight._value.shape, ("NCDHW", "OIDHW", "NCDHW"))

    def f(v, w, *maybe_b):
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=strides, padding=pad_cfg,
            rhs_dilation=dils, dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=None,
        )
        if maybe_b:
            b = maybe_b[0]
            out = out + b.reshape((1, -1) + (1,) * nd)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return apply_op(f, *[_t(a) for a in args], name=f"conv{nd}d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL"):
    return _conv_nd(_t(x), _t(weight), bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW"):
    if data_format == "NHWC":
        x = _t(x).transpose([0, 3, 1, 2])
        out = _conv_nd(x, _t(weight), bias, stride, padding, dilation, groups, 2, "NCHW")
        return out.transpose([0, 2, 3, 1])
    return _conv_nd(_t(x), _t(weight), bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW"):
    return _conv_nd(_t(x), _t(weight), bias, stride, padding, dilation, groups, 3, data_format)


def _group_transpose_kernel(w, groups, nd):
    """Paddle transpose-conv kernel (Cin, Cout/g, k...) -> XLA grouped 'IO'
    layout (Cin/g, Cout, k...): split Cin into g groups, fold the group axis
    into the output-feature dim (group-major, matching XLA's grouped-conv
    output partitioning). Identity reshape for groups == 1."""
    if groups == 1:
        return w
    cin, coutg = w.shape[0], w.shape[1]
    spatial = w.shape[2:]
    w = w.reshape((groups, cin // groups, coutg) + spatial)
    w = jnp.moveaxis(w, 0, 1)  # (Cin/g, g, Cout/g, k...)
    return w.reshape((cin // groups, groups * coutg) + spatial)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, output_size=None, data_format="NCHW"):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 2,
                              "conv2d_transpose")


def _pool(x, kernel, stride, padding, nd, reducer, init, data_format, count_include_pad=True, ceil_mode=False):
    ks = _pair(kernel, nd)
    st = _pair(stride if stride is not None else kernel, nd)
    pd = _pair(padding, nd)
    channels_last = data_format in ("NHWC", "NDHWC", "NLC")
    xv = x._value if isinstance(x, Tensor) else x
    sp = tuple(xv.shape[1:1 + nd] if channels_last else xv.shape[2:2 + nd])
    if ceil_mode:
        osp = [-(-(sp[d] + 2 * pd[d] - ks[d]) // st[d]) + 1 for d in range(nd)]
        # torch/paddle rule: the last window must start inside input+left-pad
        osp = [o - 1 if (o - 1) * st[d] >= sp[d] + pd[d] else o
               for d, o in enumerate(osp)]
    else:
        osp = [(sp[d] + 2 * pd[d] - ks[d]) // st[d] + 1 for d in range(nd)]
    # right padding so exactly osp windows exist; the part beyond the declared
    # pd is ceil-mode overhang (never counted in avg divisors)
    rp = [max((osp[d] - 1) * st[d] + ks[d] - sp[d] - pd[d], 0)
          for d in range(nd)]
    sp_pads = tuple((pd[d], rp[d]) for d in range(nd))
    if channels_last:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        pads = ((0, 0),) + sp_pads + ((0, 0),)
        slicer = ((slice(None),) + tuple(slice(0, o) for o in osp)
                  + (slice(None),))
        base_pads = ((0, 0),) + tuple((pd[d], pd[d]) for d in range(nd)) + ((0, 0),)
        extra_pads = (((0, 0),) + tuple((0, max(rp[d] - pd[d], 0)) for d in range(nd))
                      + ((0, 0),))
    else:
        window = (1, 1) + ks
        strides = (1, 1) + st
        pads = ((0, 0), (0, 0)) + sp_pads
        slicer = ((slice(None), slice(None))
                  + tuple(slice(0, o) for o in osp))
        base_pads = ((0, 0), (0, 0)) + tuple((pd[d], pd[d]) for d in range(nd))
        extra_pads = ((0, 0), (0, 0)) + tuple((0, max(rp[d] - pd[d], 0))
                                              for d in range(nd))

    def f(v):
        if reducer == "max":
            return jax.lax.reduce_window(
                v, -jnp.inf, jax.lax.max, window, strides, pads)[slicer]
        s = jax.lax.reduce_window(v, 0.0, jax.lax.add, window, strides, pads)[slicer]
        if count_include_pad and not ceil_mode:
            return s / float(np.prod(ks))
        if count_include_pad:
            # divisor counts the declared zero-padding but not ceil overhang
            ones = jnp.pad(jnp.ones_like(v), base_pads, constant_values=1.0)
            cnt = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, window, strides, extra_pads)[slicer]
        else:
            cnt = jax.lax.reduce_window(
                jnp.ones_like(v), 0.0, jax.lax.add, window, strides, pads)[slicer]
        return s / cnt

    return apply_op(f, _t(x), name=f"{reducer}_pool{nd}d")


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW"):
    if return_mask:
        if data_format != "NCHW":
            raise ValueError(
                "return_mask=True requires data_format='NCHW' (reference "
                "paddle.nn.functional.max_pool2d contract)")
        return _max_pool_with_index_nd(x, kernel_size, stride, padding, 2,
                                       ceil_mode=ceil_mode)
    return _pool(x, kernel_size, stride, padding, 2, "max", -np.inf, data_format, ceil_mode=ceil_mode)


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False):
    if return_mask:
        return _max_pool_with_index_nd(x, kernel_size, stride, padding, 1,
                                       ceil_mode=ceil_mode)
    return _pool(x, kernel_size, stride, padding, 1, "max", -np.inf, "NCL", ceil_mode=ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW"):
    return _pool(x, kernel_size, stride, padding, 2, "avg", 0.0, data_format,
                 count_include_pad=not exclusive or padding == 0,
                 ceil_mode=ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False):
    return _pool(x, kernel_size, stride, padding, 1, "avg", 0.0, "NCL",
                 count_include_pad=not exclusive or padding == 0,
                 ceil_mode=ceil_mode)


def _adaptive_bin_matrix(in_size: int, out_size: int):
    """(out_size, in_size) row-averaging matrix: row i averages the adaptive
    bin [floor(i*in/out), ceil((i+1)*in/out)) — torch/paddle bin semantics."""
    m = np.zeros((out_size, in_size), np.float32)
    for i in range(out_size):
        lo = (i * in_size) // out_size
        hi = -(-((i + 1) * in_size) // out_size)  # ceil div
        m[i, lo:hi] = 1.0 / (hi - lo)
    return m


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    os = _pair(output_size)
    x = _t(x)
    if data_format == "NCHW":
        h, w = x._value.shape[2], x._value.shape[3]
    else:
        h, w = x._value.shape[1], x._value.shape[2]
    # _pool assumes NC-leading windows, so the divisible fast path is
    # NCHW-only; NHWC always takes the einsum path
    if data_format == "NCHW" and h % os[0] == 0 and w % os[1] == 0:
        return _pool(x, (h // os[0], w // os[1]), (h // os[0], w // os[1]), 0, 2, "avg", 0.0, data_format)
    # non-divisible bins: contract with per-axis averaging matrices — two
    # skinny MXU matmuls instead of 16 gather/slice reductions
    ah = _adaptive_bin_matrix(h, os[0])
    aw = _adaptive_bin_matrix(w, os[1])

    def f(v):
        if data_format == "NCHW":
            return jnp.einsum("nchw,oh,pw->ncop", v, ah, aw,
                              preferred_element_type=v.dtype)
        return jnp.einsum("nhwc,oh,pw->nopc", v, ah, aw,
                          preferred_element_type=v.dtype)

    return apply_op(f, x, name="adaptive_avg_pool2d")


def adaptive_avg_pool1d(x, output_size):
    x = _t(x)
    l = x._value.shape[2]
    os = int(output_size)
    if l % os == 0:
        return _pool(x, l // os, l // os, 0, 1, "avg", 0.0, "NCL")
    a = _adaptive_bin_matrix(l, os)

    def f(v):
        return jnp.einsum("ncl,ol->nco", v, a, preferred_element_type=v.dtype)

    return apply_op(f, x, name="adaptive_avg_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False):
    os = _pair(output_size)
    x = _t(x)
    h, w = x._value.shape[2], x._value.shape[3]
    if h % os[0] == 0 and w % os[1] == 0:
        k = (h // os[0], w // os[1])
        if return_mask:
            return _max_pool_with_index_nd(x, k, k, 0, 2)
        return _pool(x, k, k, 0, 2, "max", -np.inf, "NCHW")

    def bins(size, out):
        return [((i * size) // out, -(-((i + 1) * size) // out)) for i in range(out)]

    hb, wb = bins(h, os[0]), bins(w, os[1])

    def f(v):
        rows = [jnp.stack([v[:, :, hl:hh, wl:wh].max(axis=(2, 3))
                           for (wl, wh) in wb], axis=-1)
                for (hl, hh) in hb]
        return jnp.stack(rows, axis=-2)

    def f_mask(v):
        outs, idxs = [], []
        for (hl, hh) in hb:
            row_o, row_i = [], []
            for (wl, wh) in wb:
                patch = v[:, :, hl:hh, wl:wh]
                bw = wh - wl
                flatp = patch.reshape(patch.shape[0], patch.shape[1], -1)
                am = jnp.argmax(flatp, axis=-1)
                row_o.append(jnp.max(flatp, axis=-1))
                # local bin argmax -> global flat h*w index (unpool contract)
                row_i.append((hl + am // bw) * w + (wl + am % bw))
            outs.append(jnp.stack(row_o, axis=-1))
            idxs.append(jnp.stack(row_i, axis=-1))
        return (jnp.stack(outs, axis=-2),
                jnp.stack(idxs, axis=-2).astype(jnp.int32))

    return apply_op(f_mask if return_mask else f, x,
                    name="adaptive_max_pool2d")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    ks = _pair(kernel_sizes)
    st = _pair(strides)
    pd = _pair(paddings)
    dl = _pair(dilations)

    def f(v):
        n, c, h, w = v.shape
        patches = jax.lax.conv_general_dilated_patches(
            v, filter_shape=ks, window_strides=st,
            padding=[(pd[0], pd[0]), (pd[1], pd[1])], rhs_dilation=dl,
            dimension_numbers=jax.lax.conv_dimension_numbers(v.shape, (1, 1) + ks, ("NCHW", "OIHW", "NCHW")),
        )
        # [N, C*kh*kw, OH, OW] -> [N, C*kh*kw, L]
        return patches.reshape(n, patches.shape[1], -1)

    return apply_op(f, _t(x), name="unfold")


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                data_format="NCHW"):
    x = _t(x)
    n, c, h, w = x._value.shape
    if size is None:
        sf = _pair(scale_factor)
        size = (int(h * sf[0]), int(w * sf[1]))
    else:
        size = _pair(size)
    method = {"nearest": "nearest", "bilinear": "bilinear", "bicubic": "cubic",
              "linear": "linear", "area": "nearest"}[mode]

    def f(v):
        return jax.image.resize(v, (v.shape[0], v.shape[1], size[0], size[1]), method=method)

    return apply_op(f, x, name="interpolate")


upsample = interpolate


def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = int(upscale_factor)

    def f(v):
        n, c, h, w = v.shape
        v = v.reshape(n, c // (r * r), r, r, h, w)
        v = v.transpose(0, 1, 4, 2, 5, 3)
        return v.reshape(n, c // (r * r), h * r, w * r)

    return apply_op(f, _t(x), name="pixel_shuffle")


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    nd = len(tuple(normalized_shape))

    def f(v, *wb):
        axes = tuple(range(v.ndim - nd, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(v - mean), axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + epsilon)
        if wb:
            if len(wb) == 2:
                out = out * wb[0] + wb[1]
            elif weight is not None:
                out = out * wb[0]
            else:
                out = out + wb[0]
        return out

    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply_op(f, *[_t(a) for a in args], name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, axis=-1):
    """RMSNorm (LLaMA-family). The composite form is the DEFAULT on purpose:
    XLA fuses it into the surrounding ops and measures ~3x faster than the
    standalone Pallas kernel (`paddle_tpu.ops.pallas.rmsnorm`, kept for
    isolated-norm workloads — see its docstring for the numbers)."""

    def f(v, *w):
        var = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=axis, keepdims=True)
        out = (v.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        if w:
            out = out * w[0]
        return out

    args = [x] if weight is None else [x, weight]
    return apply_op(f, *[_t(a) for a in args], name="rms_norm")


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None):
    x = _t(x)
    nd = x._value.ndim
    axes = tuple(i for i in range(nd) if i != 1)
    shape = [1] * nd
    shape[1] = x._value.shape[1]

    use_batch_stats = training and not use_global_stats
    if use_batch_stats:
        def f(v, *wb):
            mean = jnp.mean(v, axis=axes)
            var = jnp.var(v, axis=axes)
            out = (v - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(shape)
                i += 1
            if bias is not None:
                out = out + wb[i].reshape(shape)
            return out, mean, var

        args = [x] + [_t(a) for a in (weight, bias) if a is not None]
        out, mean, var = apply_op(f, *args, name="batch_norm")
        # running-stat EMA goes through apply_op (not raw host math) so a
        # recording static Program captures it as an instruction; _set_value
        # with the result Tensor then registers a per-run writeback
        if running_mean is not None:
            def ema(old, new):
                return momentum * old + (1 - momentum) * new

            running_mean._set_value(
                apply_op(ema, _t(running_mean), mean.detach(), name="bn_stat_update"))
            running_var._set_value(
                apply_op(ema, _t(running_var), var.detach(), name="bn_stat_update"))
        return out

    def f(v, m, va, *wb):
        out = (v - m.reshape(shape)) * jax.lax.rsqrt(va.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x, _t(running_mean), _t(running_var)] + [_t(a) for a in (weight, bias) if a is not None]
    return apply_op(f, *args, name="batch_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW"):
    x = _t(x)
    nd = x._value.ndim
    axes = tuple(range(2, nd))
    shape = [1, x._value.shape[1]] + [1] * (nd - 2)

    def f(v, *wb):
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + eps)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x] + [_t(a) for a in (weight, bias) if a is not None]
    return apply_op(f, *args, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW"):
    x = _t(x)

    def f(v, *wb):
        n, c = v.shape[0], v.shape[1]
        rest = v.shape[2:]
        g = v.reshape(n, num_groups, c // num_groups, *rest)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(v.shape)
        shape = [1, c] + [1] * len(rest)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x] + [_t(a) for a in (weight, bias) if a is not None]
    return apply_op(f, *args, name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW"):
    def f(v):
        sq = jnp.square(v)
        half = size // 2
        pads = ((0, 0), (half, size - half - 1), (0, 0), (0, 0))
        s = jax.lax.reduce_window(sq, 0.0, jax.lax.add, (1, size, 1, 1), (1, 1, 1, 1), pads)
        return v / jnp.power(k + alpha * s / size, beta)

    return apply_op(f, _t(x), name="local_response_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12):
    def f(v):
        n = jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=axis, keepdims=True), 1.0 / p)
        return v / jnp.maximum(n, epsilon)

    return apply_op(f, _t(x), name="normalize")


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------

def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return _t(x)
    from paddle_tpu.distributed.fleet.rng import current_dropout_key

    key = current_dropout_key()

    def f(v, k):
        shape = v.shape
        if axis is not None:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
            shape = tuple(s if i in axes else 1 for i, s in enumerate(v.shape))
        keep = jax.random.bernoulli(k, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0)
        return jnp.where(keep, v, 0.0)

    # key as a positional arg (not a closure) so static-graph replay can
    # substitute a fresh fold per run (rng_args marks it for the recorder)
    return apply_op(f, _t(x), key, name="dropout", rng_args=(1,))


def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    return dropout(x, p, axis=(0, 1), training=training)


def alpha_dropout(x, p=0.5, training=True):
    if not training or p == 0.0:
        return _t(x)
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = default_generator.next_key()

    def f(v, k):
        keep = jax.random.bernoulli(k, 1.0 - p, v.shape)
        a = (1.0 / math.sqrt((1 - p) * (1 + p * alpha_p ** 2))) if p < 1 else 0.0
        b = -a * alpha_p * p
        return a * jnp.where(keep, v, alpha_p) + b

    return apply_op(f, _t(x), key, name="alpha_dropout", rng_args=(1,))


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


def _fused_ce_reduce(nll, valid, reduction, out_shape, dtype):
    """Shared reduction over fp32 per-token fused-CE losses, matching the
    unfused path's semantics exactly (mean = over non-ignored tokens)."""
    if reduction == "mean":
        out = jnp.sum(nll) / jnp.maximum(jnp.sum(valid.astype(nll.dtype)), 1.0)
    elif reduction == "sum":
        out = jnp.sum(nll)
    else:
        out = nll.reshape(out_shape)
    return out.astype(dtype)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  use_fused=None):
    """reference: python/paddle/nn/functional/loss.py cross_entropy.

    Fast path: hard-label softmax CE lowers to the chunked fused kernel
    (`paddle_tpu.ops.pallas.fused_ce`) — a custom-vjp that never materializes
    the [tokens, classes] log-softmax in forward or backward. `use_fused`
    overrides the `use_fused_cross_entropy` flag per call (the escape hatch).
    """
    input = _t(input)
    nd = input._value.ndim
    fused_ok = (use_fused if use_fused is not None
                else flag("use_fused_cross_entropy"))
    if (fused_ok and use_softmax and not soft_label and weight is None
            and nd >= 2 and axis in (-1, nd - 1)):
        def f(logits, lab):
            from paddle_tpu.ops.pallas.fused_ce import (
                softmax_cross_entropy_loss)

            lv = lab
            if lv.ndim == logits.ndim:
                lv = jnp.squeeze(lv, -1)
            flat = logits.reshape(-1, logits.shape[-1])
            labf = lv.reshape(-1)
            nll = softmax_cross_entropy_loss(
                flat, labf, ignore_index=ignore_index,
                label_smoothing=label_smoothing, mp_axis=None)
            return _fused_ce_reduce(nll, labf != ignore_index, reduction,
                                    lv.shape, logits.dtype)

        return apply_op(f, input, _t(label), name="cross_entropy")

    def f(logits, lab, *w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.clip(logits, 1e-15, 1.0))
        nclass = logits.shape[axis]
        if soft_label:
            soft = lab
            if label_smoothing > 0.0:
                soft = soft * (1 - label_smoothing) + label_smoothing / nclass
            nll = -jnp.sum(soft * logp, axis=axis)
        else:
            # gather the label log-prob instead of materializing a one-hot
            # ([N, vocab] would dominate memory at LM scale)
            li = lab
            if li.ndim == logp.ndim:  # [..., 1]
                li = jnp.squeeze(li, axis)
            safe = jnp.clip(li, 0, nclass - 1)
            picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis)
            picked = jnp.squeeze(picked, axis)
            if label_smoothing > 0.0:
                nll = -(1 - label_smoothing) * picked - label_smoothing * jnp.mean(logp, axis=axis)
            else:
                nll = -picked
        if not soft_label:
            li = lab
            if li.ndim == logp.ndim:
                li = jnp.squeeze(li, axis)
            valid = li != ignore_index
            nll = jnp.where(valid, nll, 0.0)
            if w:
                cw = jnp.take(w[0], jnp.clip(li, 0, nclass - 1))
                nll = nll * cw
                if reduction == "mean":
                    denom = jnp.sum(jnp.where(valid, cw, 0.0))
                    return jnp.sum(nll) / jnp.maximum(denom, 1e-12)
            if reduction == "mean":
                return jnp.sum(nll) / jnp.maximum(jnp.sum(valid.astype(nll.dtype)), 1.0)
        return _reduce(nll, reduction)

    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return apply_op(f, *args, name="cross_entropy")


def parallel_cross_entropy(input, label, ignore_index=-100,
                           label_smoothing=0.0, use_fused=None):
    """Megatron-style vocab-parallel softmax CE (reference
    ParallelCrossEntropy, fleet/layers/mpu/mp_layers.py:742) on
    (possibly mp-sharded) logits. Returns the PER-TOKEN loss shaped like
    `label`, with ignored tokens contributing 0.

    Inside shard_map with the "mp" axis bound, `input` is the local vocab
    shard: the max / sum-exp / target-logit stats reduce over the axis with
    pmax/psum so no rank materializes a full vocab row. The hot path is the
    chunked fused kernel (custom vjp, fp32 stats); `use_fused=False` (or the
    `use_fused_cross_entropy` flag) falls back to the unfused formula."""
    input = _t(input)
    lab = _t(label)
    if lab._value.ndim == input._value.ndim:
        from paddle_tpu.ops.manipulation import squeeze

        lab = squeeze(lab, -1)
    fused_ok = (use_fused if use_fused is not None
                else flag("use_fused_cross_entropy"))
    if fused_ok:
        def f(logits, lv):
            from paddle_tpu.ops.pallas.fused_ce import (
                softmax_cross_entropy_loss)

            flat = logits.reshape(-1, logits.shape[-1])
            nll = softmax_cross_entropy_loss(
                flat, lv.reshape(-1), ignore_index=ignore_index,
                label_smoothing=label_smoothing, mp_axis="auto")
            return nll.reshape(lv.shape)

        return apply_op(f, input, lab, name="parallel_cross_entropy")

    def f(logits, lv):
        from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import (
            MP_AXIS, mp_axis_bound)

        bound = mp_axis_bound()
        lmax = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
        if bound:
            lmax = jax.lax.pmax(lmax, MP_AXIS)
        shifted = logits - lmax
        sumexp = jnp.sum(jnp.exp(shifted), axis=-1, keepdims=True)
        if bound:
            sumexp = jax.lax.psum(sumexp, MP_AXIS)
        logz = jnp.log(sumexp)
        if bound:
            n_local = logits.shape[-1]
            start = jax.lax.axis_index(MP_AXIS) * n_local
            local_lab = lv - start
            in_range = (local_lab >= 0) & (local_lab < n_local)
            safe = jnp.clip(local_lab, 0, n_local - 1)
            picked = jnp.take_along_axis(shifted, safe[..., None], axis=-1)
            picked = jnp.where(in_range[..., None], picked, 0.0)
            picked = jax.lax.psum(picked, MP_AXIS)
        else:
            picked = jnp.take_along_axis(shifted, lv[..., None], axis=-1)
        loss = (logz - picked)[..., 0]
        valid = lv != ignore_index
        return jnp.where(valid, loss, 0.0)

    return apply_op(f, input, lab, name="parallel_cross_entropy")


def fused_linear_cross_entropy(x, weight, label, bias=None, ignore_index=-100,
                               reduction="mean", label_smoothing=0.0,
                               z_loss=0.0, chunk_tokens=0, chunk_vocab=0,
                               variant="auto"):
    """loss = CE(x @ weight [+ bias], label) WITHOUT materializing the
    [tokens, vocab] logits in forward or backward (chunked custom vjp,
    `paddle_tpu.ops.pallas.fused_ce`; see docs/fused_head_cross_entropy.md).

    x: [..., hidden]; weight: [hidden, vocab] (the local shard under bound
    mp — stats then reduce over the "mp" axis, Megatron-style); label:
    integer [...] matching x's leading dims. `z_loss` adds the
    `z * logsumexp^2` stabilizer to both value and gradient."""
    x = _t(x)
    lab = _t(label)
    if lab._value.ndim == x._value.ndim:
        from paddle_tpu.ops.manipulation import squeeze

        lab = squeeze(lab, -1)

    def f(xv, wv, lv, *bv):
        from paddle_tpu.ops.pallas.fused_ce import (
            fused_linear_cross_entropy_loss)

        flat = xv.reshape(-1, xv.shape[-1])
        labf = lv.reshape(-1)
        nll = fused_linear_cross_entropy_loss(
            flat, wv, labf, bv[0] if bv else None,
            ignore_index=ignore_index, label_smoothing=label_smoothing,
            z_loss=z_loss, chunk_tokens=chunk_tokens, chunk_vocab=chunk_vocab,
            variant=variant, mp_axis="auto")
        return _fused_ce_reduce(nll, labf != ignore_index, reduction,
                                lv.shape, jnp.float32)

    args = [x, _t(weight), lab] + ([_t(bias)] if bias is not None else [])
    return apply_op(f, *args, name="fused_linear_cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index,
                         reduction="none", axis=axis)
    loss = loss.unsqueeze(axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean"):
    def f(p, y, *w):
        val = -(y * jnp.log(jnp.clip(p, 1e-12, 1.0)) + (1 - y) * jnp.log(jnp.clip(1 - p, 1e-12, 1.0)))
        if w:
            val = val * w[0]
        return _reduce(val, reduction)

    args = [_t(input), _t(label)] + ([_t(weight)] if weight is not None else [])
    return apply_op(f, *args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None):
    def f(z, y, *extra):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]; i += 1
        if pos_weight is not None:
            pw = extra[i]; i += 1
        log_sig = jax.nn.log_sigmoid(z)
        log_one_minus = jax.nn.log_sigmoid(-z)
        if pw is not None:
            val = -(pw * y * log_sig + (1 - y) * log_one_minus)
        else:
            val = -(y * log_sig + (1 - y) * log_one_minus)
        if w is not None:
            val = val * w
        return _reduce(val, reduction)

    args = [_t(logit), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    if pos_weight is not None:
        args.append(_t(pos_weight))
    return apply_op(f, *args, name="bce_with_logits")


def mse_loss(input, label, reduction="mean"):
    return apply_op(
        lambda a, b: _reduce(jnp.square(a - b), reduction), _t(input), _t(label), name="mse_loss"
    )


def square_error_cost(input, label):
    return apply_op(lambda a, b: jnp.square(a - b), _t(input), _t(label), name="square_error_cost")


def l1_loss(input, label, reduction="mean"):
    return apply_op(
        lambda a, b: _reduce(jnp.abs(a - b), reduction), _t(input), _t(label), name="l1_loss"
    )


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    def f(logp, lab, *w):
        nclass = logp.shape[-1]
        oh = jax.nn.one_hot(lab, nclass, dtype=logp.dtype)
        nll = -jnp.sum(oh * logp, axis=-1)
        valid = lab != ignore_index
        nll = jnp.where(valid, nll, 0.0)
        if w:
            cw = jnp.take(w[0], jnp.clip(lab, 0, nclass - 1))
            nll = nll * cw
        if reduction == "mean":
            denom = jnp.sum(valid.astype(nll.dtype)) if not w else jnp.sum(jnp.where(valid, jnp.take(w[0], jnp.clip(lab, 0, nclass - 1)), 0.0))
            return jnp.sum(nll) / jnp.maximum(denom, 1e-12)
        return _reduce(nll, reduction)

    args = [_t(input), _t(label)] + ([_t(weight)] if weight is not None else [])
    return apply_op(f, *args, name="nll_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    def f(a, b):
        d = jnp.abs(a - b)
        val = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(val, reduction)

    return apply_op(f, _t(input), _t(label), name="smooth_l1_loss")


def kl_div(input, label, reduction="mean", log_target=False):
    def f(logp, q):
        if log_target:
            val = jnp.exp(q) * (q - logp)
        else:
            val = q * (jnp.log(jnp.clip(q, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(val) / logp.shape[0]
        return _reduce(val, reduction)

    return apply_op(f, _t(input), _t(label), name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    return apply_op(
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        _t(input), _t(other), _t(label), name="margin_ranking_loss",
    )


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)

    return apply_op(f, _t(x1), _t(x2), name="cosine_similarity")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    return apply_op(
        lambda x, y: _reduce(jnp.where(y == 1, x, jnp.maximum(0.0, margin - x)), reduction),
        _t(input), _t(label), name="hinge_embedding_loss",
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    def f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        val = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(val, reduction)

    return apply_op(f, _t(input1), _t(input2), _t(label), name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, eps=1e-6,
                        swap=False, reduction="mean"):
    def f(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.power(jnp.abs(a - pos) + eps, p), axis=-1), 1 / p)
        dn = jnp.power(jnp.sum(jnp.power(jnp.abs(a - neg) + eps, p), axis=-1), 1 / p)
        if swap:
            dsn = jnp.power(jnp.sum(jnp.power(jnp.abs(pos - neg) + eps, p), axis=-1), 1 / p)
            dn = jnp.minimum(dn, dsn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply_op(f, _t(input), _t(positive), _t(negative), name="triplet_margin_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum"):
    def f(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        val = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            val = val / n[0]
        return _reduce(val, reduction)

    args = [_t(logit), _t(label)] + ([_t(normalizer)] if normalizer is not None else [])
    return apply_op(f, *args, name="sigmoid_focal_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (reference: warpctc-backed paddle.nn.functional.ctc_loss).

    TPU-native: optax's pure-jax forward-algorithm CTC — a lax.scan over
    time, fully differentiable and jit/shard-compatible (no warpctc
    binary). log_probs: [T, N, C] (paddle layout), labels: [N, S]."""
    import optax

    def f(lp, lab, in_len, lab_len):
        logits = jnp.transpose(lp, (1, 0, 2))  # [N, T, C]
        n, t, _ = logits.shape
        s = lab.shape[1]
        logit_pad = (jnp.arange(t)[None, :] >= in_len[:, None]).astype(jnp.float32)
        label_pad = (jnp.arange(s)[None, :] >= lab_len[:, None]).astype(jnp.float32)
        per_seq = optax.ctc_loss(logits, logit_pad, lab.astype(jnp.int32),
                                 label_pad, blank_id=blank)
        if norm_by_times:
            # reference warpctc semantics: scale only the GRADIENT by 1/T;
            # the reported loss value is unchanged. value = per_seq,
            # d(out)/d(logits) = d(per_seq)/d(logits) / T.
            t_inv = per_seq / jnp.maximum(in_len.astype(per_seq.dtype), 1)
            per_seq = t_inv + jax.lax.stop_gradient(per_seq - t_inv)
        if reduction == "mean":
            # paddle/torch 'mean': divide by label length, then batch-mean
            per_seq = per_seq / jnp.maximum(lab_len.astype(per_seq.dtype), 1)
        return _reduce(per_seq, reduction)

    return apply_op(f, _t(log_probs), _t(labels), _t(input_lengths),
                    _t(label_lengths), name="ctc_loss")


def label_smooth(label, prior_dist=None, epsilon=0.1):
    def f(y, *pd):
        n = y.shape[-1]
        if pd:
            return (1 - epsilon) * y + epsilon * pd[0]
        return (1 - epsilon) * y + epsilon / n

    args = [_t(label)] + ([_t(prior_dist)] if prior_dist is not None else [])
    return apply_op(f, *args, name="label_smooth")


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    l = _t(lengths)
    m = int(maxlen) if maxlen is not None else int(jnp.max(l._value))
    d = to_jax_dtype(dtype)
    return apply_op(
        lambda v: (jnp.arange(m)[None, :] < v[:, None]).astype(d), l, name="sequence_mask"
    )


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------

_NEG_BIAS = -1e30  # additive mask floor: composes (sums) without fp32
                   # overflow, unlike finfo.min whose sum is -inf -> NaN

_warned_pallas_blocks: set = set()


def _warn_pallas_blocks_once(reason: str, shape_sig=None):
    """One-time XLA-fallback warning, deduplicated per (reason, shape
    signature) — NOT per process: a second, DISTINCT fallback cause (a new
    reason, or the same reason triggered by a different q/k/v geometry)
    must still surface instead of being swallowed by the first one."""
    key = (reason, shape_sig)
    if key not in _warned_pallas_blocks:
        import warnings

        _warned_pallas_blocks.add(key)
        at = f" (shapes {shape_sig})" if shape_sig is not None else ""
        warnings.warn(
            f"Pallas flash attention disabled for this shape{at}, using the "
            f"XLA fallback: {reason}", stacklevel=3)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None,
                                 segment_ids=None):
    """reference: nn/functional/flash_attention.py:722 scaled_dot_product_attention.

    Layout: [batch, seq, heads, head_dim] (paddle flash-attention convention).
    Uses the Pallas flash-attention kernel on TPU when enabled+applicable,
    else an XLA fallback (fused by the compiler; memory O(S^2) only at trace).

    segment_ids ([batch, seq] int32, sequence packing): attention becomes
    block-diagonal per packed document — position i attends to j only when
    segment_ids[b, i] == segment_ids[b, j] (composed with the causal and
    explicit masks). The Pallas kernel additionally SKIPS whole K blocks no
    segment of the Q block touches; the XLA fallback applies the equivalent
    dense mask so both paths compute the same math.

    Masks COMPOSE: an explicit `attn_mask` together with `is_causal=True`
    (and/or `segment_ids`) applies all of them — boolean masks and the
    causal/segment constraints become additive -1e30 biases, float masks add
    through unchanged, so no combination overflows to -inf/NaN.
    """
    if flag("use_pallas_attention") and dropout_p == 0.0 and attn_mask is None:
        try:
            # guarded: a jax install without a working pallas import must
            # degrade to the XLA path, not break every attention call
            from paddle_tpu.ops.pallas.flash_attention import (
                _on_tpu, flash_attention_bshd, interpret_forced,
                pallas_blocks_ok)
            pallas_route = _on_tpu() or interpret_forced()
        except Exception:
            pallas_route = False
        if pallas_route:
            ok, reason = pallas_blocks_ok(int(_t(query).shape[1]))
            if not ok:
                # a bad FLAGS_flash_block_q/k override must not fail inside
                # the kernel launch: warn once PER (cause, geometry), run
                # the XLA path below
                _warn_pallas_blocks_once(
                    reason, shape_sig=tuple(_t(query).shape))
            else:
                try:
                    q, k, v = _t(query), _t(key), _t(value)
                    args = [q, k, v]
                    if segment_ids is not None:
                        args.append(_t(segment_ids))

                    def fa(a, b, c, *s):
                        return flash_attention_bshd(
                            a, b, c, causal=is_causal,
                            segment_ids=s[0] if s else None)

                    return apply_op(fa, *args, name="flash_attention")
                except Exception:
                    if interpret_forced():
                        # the tests' force_interpret() route exists to
                        # exercise the kernel: swallowing a kernel failure
                        # here would silently downgrade the parity tests
                        # to XLA-vs-XLA
                        raise
                    pass  # fall back to XLA path below

    def f(q, k, v, *extra):
        # [B,S,H,D] -> [B,H,S,D]; GQA (fewer kv heads) via grouped einsum —
        # the shared K/V heads are never materialized per query head
        it = iter(extra)
        m = next(it) if attn_mask is not None else None
        seg = next(it) if segment_ids is not None else None
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        b, hq, s_len, d = qh.shape
        hkv = kh.shape[1]
        if hkv == 0 or hq % hkv != 0:
            raise ValueError(
                f"q heads must be a multiple of kv heads, got {hq} and {hkv}")
        g = hq // hkv
        qg = qh.reshape(b, hkv, g, s_len, d)
        scores = jnp.einsum("bhgsd,bhtd->bhgst", qg, kh).astype(
            jnp.float32) / math.sqrt(q.shape[-1])
        t_len = scores.shape[-1]
        # masks COMPOSE in two tiers: HARD masks (bool attn_mask, causal,
        # segment) combine into one validity boolean; a SOFT (float)
        # attn_mask adds through, clamped to -1e30 so a finfo.min-style
        # user mask neither overflows to -inf/NaN nor outranks a hard mask
        # (hard-masked scores sit strictly below every soft-masked one).
        valid = None
        if m is not None:
            mask = jnp.broadcast_to(m, (b, hq, s_len, t_len))
            mask = mask.reshape(b, hkv, g, s_len, t_len)
            if mask.dtype == jnp.bool_:
                valid = mask
            else:
                scores = scores + jnp.maximum(
                    mask.astype(jnp.float32), _NEG_BIAS)
        if is_causal:
            causal = jnp.tril(jnp.ones((s_len, t_len), bool))
            valid = causal if valid is None else valid & causal
        if seg is not None:
            same = seg[:, None, None, :, None] == seg[:, None, None, None, :]
            valid = same if valid is None else valid & same
        if valid is not None:
            scores = jnp.where(valid, scores, 2.0 * _NEG_BIAS)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        out = jnp.einsum("bhgst,bhtd->bhgsd", probs, vh).reshape(b, hq, s_len, d)
        return jnp.swapaxes(out, 1, 2)

    args = [_t(query), _t(key), _t(value)]
    if attn_mask is not None:
        args.append(_t(attn_mask))
    if segment_ids is not None:
        args.append(_t(segment_ids))
    out = apply_op(f, *args, name="sdpa")
    if dropout_p > 0.0 and training:
        out = dropout(out, dropout_p, training=training)
    return out


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True, name=None):
    """reference: nn/functional/flash_attention.py:147."""
    out = scaled_dot_product_attention(
        query, key, value, dropout_p=dropout, is_causal=causal, training=training
    )
    if return_softmax:
        return out, None
    return out, None


# ---------------------------------------------------------------------------
# functional tail (reference ops.yaml: huber_loss, log_loss, channel_shuffle,
# pixel_unshuffle, temporal_shift, gumbel_softmax, swiglu, lp_pool2d,
# max_pool2d_with_index/unpool, affine_grid, grid_sample, fold)

def huber_loss(input, label, delta=1.0, reduction="mean"):
    def f(x, y):
        d = x - y
        ad = jnp.abs(d)
        return _reduce(jnp.where(ad <= delta, 0.5 * d * d,
                                 delta * (ad - 0.5 * delta)), reduction)

    return apply_op(f, _t(input), _t(label), name="huber_loss")


def log_loss(input, label, epsilon=1e-4):
    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1.0 - y) * jnp.log(1.0 - p + epsilon)

    return apply_op(f, _t(input), _t(label), name="log_loss")


def channel_shuffle(x, groups, data_format="NCHW"):
    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            return v.reshape(n, groups, c // groups, h, w) \
                    .transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = v.shape
        return v.reshape(n, h, w, groups, c // groups) \
                .transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)

    return apply_op(f, _t(x), name="channel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = int(downscale_factor)

    def f(v):
        n, c, h, w = v.shape
        v = v.reshape(n, c, h // r, r, w // r, r)
        return v.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r, h // r, w // r)

    return apply_op(f, _t(x), name="pixel_unshuffle")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    def f(v):
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        fold_c = int(c * shift_ratio)
        back = jnp.concatenate([v[:, 1:, :fold_c],
                                jnp.zeros_like(v[:, :1, :fold_c])], axis=1)
        fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, fold_c:2 * fold_c]),
                               v[:, :-1, fold_c:2 * fold_c]], axis=1)
        keep = v[:, :, 2 * fold_c:]
        return jnp.concatenate([back, fwd, keep], axis=2).reshape(nt, c, h, w)

    return apply_op(f, _t(x), name="temporal_shift")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    from paddle_tpu.ops.random_state import default_generator

    key = default_generator.next_key()

    def f(v, k):
        u = jax.random.uniform(k, v.shape, v.dtype, 1e-20, 1.0)
        g = -jnp.log(-jnp.log(u))
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            oh = jax.nn.one_hot(jnp.argmax(y, axis=axis), v.shape[axis],
                                axis=axis, dtype=v.dtype)
            return oh + y - jax.lax.stop_gradient(y)  # straight-through
        return y

    return apply_op(f, _t(x), key, name="gumbel_softmax", rng_args=(1,))


def swiglu(x, y=None):
    """reference ops.yaml swiglu: silu(x) * y, with y defaulting to the
    second half of x split on the last axis (fused-FFN gate)."""
    if y is not None:
        return apply_op(lambda a, b: jax.nn.silu(a) * b, _t(x), _t(y),
                        name="swiglu")

    def f(v):
        a, b = jnp.split(v, 2, axis=-1)
        return jax.nn.silu(a) * b

    return apply_op(f, _t(x), name="swiglu")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW"):
    p = float(norm_type)
    ks = _pair(kernel_size, 2)
    st = _pair(stride if stride is not None else kernel_size, 2)
    pd = _pair(padding, 2)

    def f(v):
        s = jax.lax.reduce_window(
            jnp.abs(v) ** p, 0.0, jax.lax.add, (1, 1) + ks, (1, 1) + st,
            ((0, 0), (0, 0)) + tuple((q, q) for q in pd))
        return s ** (1.0 / p)

    return apply_op(f, _t(x), name="lp_pool2d")


def _max_pool_with_index_nd(x, kernel_size, stride, padding, nd,
                            ceil_mode=False):
    """N-d max pool returning (out, flat-spatial argmax indices) — the
    machinery behind max_pool2d_with_index and every return_mask=True pool
    (reference ops.yaml max_pool2d_with_index; feeds max_unpool*d).
    Indices are exact int32 arithmetic (window start + in-window offset),
    not a float gather — no 2^24 precision cliff on large volumes."""
    ks = _pair(kernel_size, nd)
    st = _pair(stride if stride is not None else kernel_size, nd)
    pd = _pair(padding, nd)

    def f(v):
        n, c = v.shape[0], v.shape[1]
        sp = v.shape[2:]
        if ceil_mode:
            osp_t = [-(-(sp[d] + 2 * pd[d] - ks[d]) // st[d]) + 1
                     for d in range(nd)]
            # torch/paddle: the last window must start inside input+left-pad
            osp_t = [o - 1 if (o - 1) * st[d] >= sp[d] + pd[d] else o
                     for d, o in enumerate(osp_t)]
        else:
            osp_t = [(sp[d] + 2 * pd[d] - ks[d]) // st[d] + 1
                     for d in range(nd)]
        # right-pad enough that every ceil-mode window exists; finite
        # dtype-min padding (NOT -inf: the patches extraction is a one-hot
        # conv and -inf * 0 = NaN) never wins an argmax — windows always
        # overlap valid input
        padw = ((0, 0), (0, 0)) + tuple(
            (pd[d], max((osp_t[d] - 1) * st[d] + ks[d] - sp[d] - pd[d], 0))
            for d in range(nd))
        vpad = jnp.pad(v, padw, constant_values=jnp.finfo(v.dtype).min)
        patches = jax.lax.conv_general_dilated_patches(
            vpad, ks, st, "VALID")  # (N, C*prod(ks), *osp) channel-major
        patches = patches[(slice(None), slice(None))
                          + tuple(slice(0, o) for o in osp_t)]
        osp = patches.shape[2:]
        kprod = int(np.prod(ks))
        pr = patches.reshape((n, c, kprod) + osp)
        am = jnp.argmax(pr, axis=2)
        out = jnp.take_along_axis(pr, am[:, :, None], axis=2)[:, :, 0]
        # decompose the in-window argmax (row-major over ks) and add the
        # window start to get exact global per-dim coords -> flat index
        rem = am.astype(jnp.int32)
        flat = jnp.zeros(am.shape, jnp.int32)
        for d in range(nd):
            k_rest = int(np.prod(ks[d + 1:], dtype=np.int64))
            off_d = rem // k_rest
            rem = rem % k_rest
            bshape = [1, 1] + [1] * nd
            bshape[2 + d] = osp[d]
            start_d = (jnp.arange(osp[d], dtype=jnp.int32) * st[d]
                       - pd[d]).reshape(bshape)
            flat = flat * sp[d] + (off_d + start_d)
        return out, flat

    return apply_op(f, _t(x), name=f"max_pool{nd}d_with_index")


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0,
                          ceil_mode=False):
    """Max pool returning flat (h*w) argmax indices per output cell
    (reference ops.yaml max_pool2d_with_index; feeds max_unpool2d)."""
    return _max_pool_with_index_nd(x, kernel_size, stride, padding, 2)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW"):
    ks = _pair(kernel_size, 2)
    st = _pair(stride if stride is not None else kernel_size, 2)

    def f(v, idx):
        n, c, oh, ow = v.shape
        if output_size is not None:
            hh, ww = int(output_size[-2]), int(output_size[-1])
        else:
            hh = (oh - 1) * st[0] + ks[0] - 2 * _pair(padding, 2)[0]
            ww = (ow - 1) * st[1] + ks[1] - 2 * _pair(padding, 2)[1]
        flat = jnp.zeros((n, c, hh * ww), v.dtype)
        out = flat.at[
            jnp.arange(n)[:, None, None],
            jnp.arange(c)[None, :, None],
            idx.reshape(n, c, -1),
        ].set(v.reshape(n, c, -1))
        return out.reshape(n, c, hh, ww)

    return apply_op(f, _t(x), _t(indices), name="max_unpool2d")


def affine_grid(theta, out_shape, align_corners=True):
    """reference ops.yaml affine_grid: sampling grid from 2x3 affine maps."""
    n, c, h, w = [int(s) for s in out_shape]

    def f(th):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1.0
            xs = (jnp.arange(w) * 2 + 1) / w - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # (H, W, 3)
        # sampling coordinates must not go through the bf16 MXU default —
        # a 1e-3 coordinate error visibly blurs the resample
        return jnp.einsum("hwk,njk->nhwj", base.astype(th.dtype), th,
                          precision=jax.lax.Precision.HIGHEST)

    return apply_op(f, _t(theta), name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """reference ops.yaml grid_sample: NCHW bilinear/nearest sampling at
    normalized grid locations with zeros/border/reflection padding."""

    def f(v, g):
        n, c, h, w = v.shape
        gx, gy = g[..., 0], g[..., 1]

        def unnorm(coord, size):
            if align_corners:
                return (coord + 1.0) * 0.5 * (size - 1)
            return ((coord + 1.0) * size - 1.0) * 0.5

        ix = unnorm(gx, w)
        iy = unnorm(gy, h)

        def reflect(coord, size):
            if align_corners:
                span = 2.0 * (size - 1)
                coord = jnp.abs(jnp.mod(coord, span))
                return jnp.where(coord > size - 1, span - coord, coord)
            span = 2.0 * size
            coord = jnp.mod(coord + 0.5, span)
            coord = jnp.abs(coord)
            coord = jnp.where(coord > size, span - coord, coord) - 0.5
            return jnp.clip(coord, 0, size - 1)

        if padding_mode == "reflection":
            ix = reflect(ix, w)
            iy = reflect(iy, h)
        elif padding_mode == "border":
            ix = jnp.clip(ix, 0, w - 1)
            iy = jnp.clip(iy, 0, h - 1)

        def gather(yi, xi):
            yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
            got = v[jnp.arange(n)[:, None, None], :, yc, xc]  # (N, Hg, Wg, C)
            if padding_mode == "zeros":
                ok = ((yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1))
                got = got * ok[..., None].astype(got.dtype)
            return got

        if mode == "nearest":
            out = gather(jnp.round(iy), jnp.round(ix))
            return jnp.moveaxis(out, -1, 1)

        x0 = jnp.floor(ix)
        y0 = jnp.floor(iy)
        x1, y1 = x0 + 1, y0 + 1
        wx = ix - x0
        wy = iy - y0
        out = (gather(y0, x0) * ((1 - wx) * (1 - wy))[..., None]
               + gather(y0, x1) * (wx * (1 - wy))[..., None]
               + gather(y1, x0) * ((1 - wx) * wy)[..., None]
               + gather(y1, x1) * (wx * wy)[..., None])
        return jnp.moveaxis(out, -1, 1)

    return apply_op(f, _t(x), _t(grid), name="grid_sample")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im (reference ops.yaml fold): scatter-add unfolded columns back
    into the spatial map — inverse of `unfold`."""
    oh, ow = _pair(output_sizes, 2)
    kh, kw = _pair(kernel_sizes, 2)
    sh, sw = _pair(strides, 2)
    ph, pw = _pair(paddings, 2)
    dh, dw = _pair(dilations, 2)
    lh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    lw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1

    def f(v):
        n = v.shape[0]
        c = v.shape[1] // (kh * kw)
        cols = v.reshape(n, c, kh, kw, lh, lw)
        out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), v.dtype)
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :,
                             i * dh: i * dh + lh * sh: sh,
                             j * dw: j * dw + lw * sw: sw].add(cols[:, :, i, j])
        return out[:, :, ph: ph + oh, pw: pw + ow]

    return apply_op(f, _t(x), name="fold")


__all__ += [
    "huber_loss", "log_loss", "channel_shuffle", "pixel_unshuffle",
    "temporal_shift", "gumbel_softmax", "swiglu", "lp_pool2d",
    "max_pool2d_with_index", "max_unpool2d", "affine_grid", "grid_sample",
    "fold",
]


# ---------------------------------------------------------------------------
# loss tail (reference nn/functional/loss.py: gaussian_nll_loss,
# poisson_nll_loss, multi_label_soft_margin_loss, soft_margin_loss,
# triplet_margin_with_distance_loss)

def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    def f(mu, y, var):
        var = jnp.maximum(var, epsilon)
        val = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            val = val + 0.5 * math.log(2 * math.pi)
        return _reduce(val, reduction)

    return apply_op(f, _t(input), _t(label), _t(variance),
                    name="gaussian_nll_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean"):
    def f(x, y):
        if log_input:
            val = jnp.exp(x) - y * x
        else:
            val = x - y * jnp.log(x + epsilon)
        if full:
            # stirling term for y > 1
            stir = y * jnp.log(y) - y + 0.5 * jnp.log(2 * math.pi * y)
            val = val + jnp.where(y > 1, stir, 0.0)
        return _reduce(val, reduction)

    return apply_op(f, _t(input), _t(label), name="poisson_nll_loss")


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean"):
    def f(x, y, *w):
        val = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            val = val * w[0]
        return _reduce(val.mean(axis=-1), reduction)

    args = [_t(input), _t(label)] + ([_t(weight)] if weight is not None else [])
    return apply_op(f, *args, name="multi_label_soft_margin_loss")


def soft_margin_loss(input, label, reduction="mean"):
    def f(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)

    return apply_op(f, _t(input), _t(label), name="soft_margin_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean"):
    dist = distance_function or (
        lambda a, b: paddle_pairwise_distance(a, b))

    d_ap = dist(_t(input), _t(positive))
    d_an = dist(_t(input), _t(negative))
    if swap:
        d_pn = dist(_t(positive), _t(negative))
        d_an = apply_op(jnp.minimum, d_an, d_pn, name="triplet_swap")

    def f(ap, an):
        return _reduce(jnp.maximum(ap - an + margin, 0.0), reduction)

    return apply_op(f, d_ap, d_an, name="triplet_margin_with_distance_loss")


def paddle_pairwise_distance(x, y, p=2.0, epsilon=1e-6):
    return apply_op(
        lambda a, b: ((jnp.abs(a - b) + epsilon) ** p).sum(-1) ** (1.0 / p),
        _t(x), _t(y), name="pairwise_distance")


__all__ += [
    "gaussian_nll_loss", "poisson_nll_loss", "multi_label_soft_margin_loss",
    "soft_margin_loss", "triplet_margin_with_distance_loss",
]


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean"):
    """RNN-Transducer loss (reference: warp-transducer-backed
    nn/functional/loss.py rnnt_loss:1983).

    TPU-native: the transducer forward algorithm as a lax.scan over frames
    with an inner scan over label positions — pure jax, differentiable,
    jit/shard-compatible (no warprnnt binary). input: [B, T, U+1, D]
    log-probs, label: [B, U]. fastemit_lambda applies FastEmit's (1+lambda)
    label-emission weighting inside the DP (the gradient-scaling form of
    warp-transducer, folded into the objective)."""
    import math as _math

    NEG = -1e30

    def f(lp, y, t_len, u_len):
        b, t_max, u_max1, _ = lp.shape
        u_max = u_max1 - 1
        blank_lp = lp[..., blank]                          # [B, T, U+1]
        lab_lp = jnp.take_along_axis(
            lp[:, :, :u_max, :], y[:, None, :, None].astype(jnp.int32),
            axis=-1)[..., 0]                              # [B, T, U]
        if fastemit_lambda:
            lab_lp = lab_lp + _math.log1p(fastemit_lambda)

        u_idx = jnp.arange(u_max1)
        u_valid = u_idx[None, :] <= u_len[:, None]        # [B, U+1]

        def u_step(carry, inp):
            # carry: alpha row being built (prefix over u); inp: (A_u, l_{u-1})
            prev, = carry
            a_u, l_prev = inp
            cur = jnp.logaddexp(a_u, prev + l_prev)
            return (cur,), cur

        def t_step(alpha_prev, t):
            # alpha_prev: [B, U+1] for frame t-1 -> alpha for frame t
            A = alpha_prev + blank_lp[:, t - 1, :]        # horizontal (blank) moves
            lab_t = lab_lp[:, t, :]                       # vertical moves in frame t

            def row(a_b, lab_b):
                first = a_b[0]
                (_, ), rest = jax.lax.scan(
                    u_step, (first,), (a_b[1:], lab_b))
                return jnp.concatenate([first[None], rest])

            alpha = jax.vmap(row)(A, lab_t)
            return jnp.where(u_valid, alpha, NEG), None

        # frame 0: only vertical moves from alpha[0,0]=0
        def row0(lab_b):
            init = jnp.zeros(())
            (_, ), rest = jax.lax.scan(
                u_step, (init,), (jnp.full((u_max,), NEG), lab_b))
            return jnp.concatenate([init[None], rest])

        alpha0 = jnp.where(u_valid, jax.vmap(row0)(lab_lp[:, 0, :]), NEG)

        def fori_body(t, alpha_all):
            alpha, final = alpha_all
            new_alpha, _ = t_step(alpha, t)
            active = (t < t_len)[:, None]
            alpha = jnp.where(active, new_alpha, alpha)
            # when t == t_len-1 this frame is the last: record terminal value
            at_end = (t == t_len - 1)
            term = jnp.take_along_axis(
                alpha + blank_lp[:, jnp.minimum(t, t_max - 1), :],
                u_len[:, None].astype(jnp.int32), axis=1)[:, 0]
            final = jnp.where(at_end, term, final)
            return (alpha, final)

        final0 = jnp.take_along_axis(
            alpha0 + blank_lp[:, 0, :], u_len[:, None].astype(jnp.int32),
            axis=1)[:, 0]
        final0 = jnp.where(t_len == 1, final0, NEG)
        alpha, final = jax.lax.fori_loop(1, t_max, fori_body, (alpha0, final0))
        per_seq = -final
        if reduction == "mean":
            per_seq = per_seq / jnp.maximum(u_len.astype(per_seq.dtype), 1)
        return _reduce(per_seq, reduction)

    return apply_op(f, _t(input), _t(label), _t(input_lengths),
                    _t(label_lengths), name="rnnt_loss")


__all__ += ["rnnt_loss"]


# ---------------------------------------------------------------------------
# functional tail 2: 3-D pools, pads, metric-learning losses, edit distance

def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    if return_mask:
        if data_format != "NCDHW":
            raise ValueError(
                "return_mask=True requires data_format='NCDHW' (reference "
                "paddle.nn.functional.max_pool3d contract)")
        return _max_pool_with_index_nd(x, kernel_size, stride, padding, 3,
                                       ceil_mode=ceil_mode)
    return _pool(x, kernel_size, stride, padding, 3, "max", -np.inf,
                 data_format, ceil_mode=ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW"):
    return _pool(x, kernel_size, stride, padding, 3, "avg", 0.0, data_format,
                 count_include_pad=not exclusive or padding == 0,
                 ceil_mode=ceil_mode)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    os3 = ((output_size,) * 3 if isinstance(output_size, int)
           else tuple(output_size))
    x = _t(x)
    if data_format == "NCDHW":
        d, h, w = x._value.shape[2:5]
    else:  # NDHWC
        d, h, w = x._value.shape[1:4]
    if (data_format == "NCDHW" and d % os3[0] == 0 and h % os3[1] == 0
            and w % os3[2] == 0):
        k = (d // os3[0], h // os3[1], w // os3[2])
        return _pool(x, k, k, 0, 3, "avg", 0.0, data_format)
    mats = [_adaptive_bin_matrix(s, o) for s, o in zip((d, h, w), os3)]

    def f(v):
        if data_format == "NCDHW":
            return jnp.einsum("ncdhw,od,ph,qw->ncopq", v, *mats,
                              preferred_element_type=v.dtype)
        return jnp.einsum("ndhwc,od,ph,qw->nopqc", v, *mats,
                          preferred_element_type=v.dtype)

    return apply_op(f, x, name="adaptive_avg_pool3d")


def zeropad2d(x, padding, data_format="NCHW"):
    p = padding if not isinstance(padding, int) else [padding] * 4

    def f(v):
        return jnp.pad(v, ((0, 0), (0, 0), (p[2], p[3]), (p[0], p[1])))

    return apply_op(f, _t(x), name="zeropad2d")


def pad3d(x, paddings, mode="constant", value=0.0, data_format="NCDHW"):
    p = paddings if not isinstance(paddings, int) else [paddings] * 6

    def f(v):
        pads = ((0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1]))
        if mode == "constant":
            return jnp.pad(v, pads, constant_values=value)
        m = {"reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
        return jnp.pad(v, pads, mode=m)

    return apply_op(f, _t(x), name="pad3d")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """reference loss.py npair_loss: cross-entropy over anchor-positive
    similarities + L2 on the embeddings."""

    def f(a, p, y):
        sim = a @ p.T  # [B, B]
        same = (y[:, None] == y[None, :]).astype(sim.dtype)
        tgt = same / same.sum(-1, keepdims=True)
        xent = (-tgt * jax.nn.log_softmax(sim, axis=-1)).sum(-1).mean()
        reg = l2_reg * (jnp.sum(a * a) + jnp.sum(p * p)) / a.shape[0] * 0.25
        return xent + reg

    return apply_op(f, _t(anchor), _t(positive), _t(labels), name="npair_loss")


def dice_loss(input, label, epsilon=1e-5):
    """reference loss.py dice_loss: 1 - 2|X∩Y| / (|X|+|Y|) over the
    one-hot label (input: [..., C] probabilities, label: [..., 1] ids)."""

    def f(x, y):
        oh = jax.nn.one_hot(y[..., 0].astype(jnp.int32), x.shape[-1],
                            dtype=x.dtype)
        reduce_dims = tuple(range(1, x.ndim))
        inter = jnp.sum(x * oh, axis=reduce_dims)
        union = jnp.sum(x, axis=reduce_dims) + jnp.sum(oh, axis=reduce_dims)
        return jnp.mean(1.0 - 2.0 * inter / (union + epsilon))

    return apply_op(f, _t(input), _t(label), name="dice_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """ArcFace-family margin softmax (reference loss.py margin_cross_entropy:
    cos(m1*theta + m2) - m3 on the target logit, then scaled CE)."""

    def f(lg, y):
        yi = y.astype(jnp.int32).reshape(-1)
        oh = jax.nn.one_hot(yi, lg.shape[-1], dtype=lg.dtype)
        cos = jnp.clip(lg, -1.0, 1.0)
        theta = jnp.arccos(cos)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        adj = jnp.where(oh > 0, target, cos) * scale
        lsm = jax.nn.log_softmax(adj, axis=-1)
        loss = -(oh * lsm).sum(-1)
        if reduction == "none":
            out_loss = loss
        elif reduction == "sum":
            out_loss = loss.sum()
        else:
            out_loss = loss.mean()
        if return_softmax:
            return out_loss, jnp.exp(lsm)
        return out_loss

    return apply_op(f, _t(logits), _t(label), name="margin_cross_entropy")


def embedding_bag(input, weight, mode="mean", padding_idx=None, name=None):
    """Sum/mean/max over each row's embedded ids (reference embedding_bag)."""

    def f(ids, w):
        emb = jnp.take(w, ids.astype(jnp.int32), axis=0)  # [B, L, D]
        if padding_idx is not None:
            mask = (ids != padding_idx)[..., None].astype(emb.dtype)
            emb = emb * mask
            denom = jnp.maximum(mask.sum(axis=-2), 1.0)
        else:
            denom = jnp.asarray(ids.shape[-1], emb.dtype)
        if mode == "sum":
            return emb.sum(axis=-2)
        if mode == "max":
            return emb.max(axis=-2)
        return emb.sum(axis=-2) / denom

    return apply_op(f, _t(input), _t(weight), name="embedding_bag")


def edit_distance(input, label, normalized=True, ignored_tokens=None):
    """Levenshtein distance per sequence pair (reference edit_distance op;
    host DP — dynamic-length string metric, not a device op)."""
    a_np = np.asarray(_t(input)._value)
    b_np = np.asarray(_t(label)._value)

    def lev(a, b):
        if ignored_tokens:
            a = [x for x in a if x not in ignored_tokens]
            b = [x for x in b if x not in ignored_tokens]
        m, n = len(a), len(b)
        dp = list(range(n + 1))
        for i in range(1, m + 1):
            prev = dp[0]
            dp[0] = i
            for j in range(1, n + 1):
                cur = dp[j]
                dp[j] = min(dp[j] + 1, dp[j - 1] + 1,
                            prev + (a[i - 1] != b[j - 1]))
                prev = cur
        return dp[n], n

    out, counts = [], []
    for a, b in zip(np.atleast_2d(a_np), np.atleast_2d(b_np)):
        d, n = lev(list(a), list(b))
        out.append(d / max(n, 1) if normalized else d)
        counts.append(1)
    return (Tensor(jnp.asarray(np.asarray(out, np.float32)[:, None])),
            Tensor(jnp.asarray(np.asarray(counts, np.int64))))


__all__ += [
    "max_pool3d", "avg_pool3d", "adaptive_avg_pool3d", "zeropad2d", "pad3d",
    "npair_loss", "dice_loss", "margin_cross_entropy", "embedding_bag",
    "edit_distance",
]


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, nd, name):
    """Shared N-d transpose conv: lhs-dilated conv with flipped IO kernel
    (the XLA-native formulation — no col2im scatter)."""
    strides = _pair(stride, nd)
    pads = _pair(padding, nd)
    dils = _pair(dilation, nd)
    opad = _pair(output_padding, nd)
    spatial = "DHW"[3 - nd:]
    io = ("NC" + spatial, "IO" + spatial, "NC" + spatial)
    xv = x._value if isinstance(x, Tensor) else x
    wv_shape = (weight._value.shape if isinstance(weight, Tensor)
                else weight.shape)
    grouped_shape = ((wv_shape[0] // groups, wv_shape[1] * groups)
                     + tuple(wv_shape[2:]))
    dn = jax.lax.conv_dimension_numbers(xv.shape, grouped_shape, io)
    pad_cfg = [
        (dils[i] * (wv_shape[2 + i] - 1) - pads[i],
         dils[i] * (wv_shape[2 + i] - 1) - pads[i] + opad[i])
        for i in range(nd)
    ]
    spatial_axes = tuple(range(2, 2 + nd))

    def f(v, w, *maybe_b):
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=(1,) * nd, padding=pad_cfg,
            lhs_dilation=strides, rhs_dilation=dils, dimension_numbers=dn,
            feature_group_count=groups)
        if maybe_b:
            out = out + maybe_b[0].reshape((1, -1) + (1,) * nd)
        return out

    w = _t(weight)
    flip_w = apply_op(
        lambda u: _group_transpose_kernel(
            jnp.flip(u, axis=spatial_axes), groups, nd),
        w, name="flip")
    args = (_t(x), flip_w) if bias is None else (_t(x), flip_w, _t(bias))
    return apply_op(f, *args, name=name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, output_size=None,
                     data_format="NCL"):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 1,
                              "conv1d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, output_size=None,
                     data_format="NCDHW"):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 3,
                              "conv3d_transpose")


__all__ += ["conv1d_transpose", "conv3d_transpose"]
