"""Weight initializers (reference: python/paddle/nn/initializer) + ParamAttr
(reference: python/paddle/base/param_attr.py). Initializers are callables
(shape, jax_dtype) -> jax array, drawing from the global generator so
`paddle_tpu.seed` makes init deterministic."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.random_state import default_generator

__all__ = [
    "ParamAttr", "Initializer", "Constant", "Normal", "TruncatedNormal",
    "Uniform", "XavierNormal", "XavierUniform", "KaimingNormal",
    "KaimingUniform", "Assign", "Orthogonal", "calculate_gain",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
        "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle linear weights are [in, out]
        return shape[0], shape[1]
    # conv [out_c, in_c, *k]
    rf = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    return shape[1] * rf, shape[0] * rf


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        key = default_generator.next_key()
        return self.mean + self.std * jax.random.normal(key, tuple(shape), dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        key = default_generator.next_key()
        return self.mean + self.std * jax.random.truncated_normal(
            key, self.a, self.b, tuple(shape), dtype
        )


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        key = default_generator.next_key()
        return jax.random.uniform(key, tuple(shape), dtype, self.low, self.high)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        key = default_generator.next_key()
        return jax.random.uniform(key, tuple(shape), dtype, -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        key = default_generator.next_key()
        return std * jax.random.normal(key, tuple(shape), dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.gain = calculate_gain(nonlinearity, negative_slope)

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        limit = self.gain * math.sqrt(3.0 / fi)
        key = default_generator.next_key()
        return jax.random.uniform(key, tuple(shape), dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.gain = calculate_gain(nonlinearity, negative_slope)

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        std = self.gain / math.sqrt(fi)
        key = default_generator.next_key()
        return std * jax.random.normal(key, tuple(shape), dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from paddle_tpu.core.tensor import Tensor

        v = self.value._value if isinstance(self.value, Tensor) else np.asarray(self.value)
        arr = jnp.asarray(v, dtype)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(tuple(shape))
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        key = default_generator.next_key()
        return self.gain * jax.nn.initializers.orthogonal()(key, tuple(shape), dtype)


class ParamAttr:
    """reference: python/paddle/base/param_attr.py ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip
