from paddle_tpu.nn.layer import activation, common, conv, layers, loss, norm, pooling, rnn, transformer  # noqa: F401
