"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["ReLU", "ReLU6", "GELU", "Sigmoid", "Silu", "Swish", "Tanh", "Softmax",
           "LogSoftmax", "LeakyReLU", "ELU", "SELU", "CELU", "Hardswish",
           "Hardsigmoid", "Hardtanh", "Hardshrink", "Softshrink", "Softplus",
           "Softsign", "Tanhshrink", "ThresholdedReLU", "LogSigmoid", "Mish",
           "GLU", "PReLU", "Maxout"]


def _layer(fn_name, *defaults):
    class _Act(Layer):
        def __init__(self, *args, name=None, **kwargs):
            super().__init__()
            self._args = args if args else defaults
            self._kwargs = {k: v for k, v in kwargs.items() if k != "name"}

        def forward(self, x):
            return getattr(F, fn_name)(x, *self._args, **self._kwargs)

    _Act.__name__ = fn_name
    return _Act


ReLU = _layer("relu")
ReLU6 = _layer("relu6")
Sigmoid = _layer("sigmoid")
Silu = _layer("silu")
Swish = _layer("swish")
Tanh = _layer("tanh")
LogSigmoid = _layer("log_sigmoid")
Mish = _layer("mish")
Hardswish = _layer("hardswish")
Hardsigmoid = _layer("hardsigmoid")
Tanhshrink = _layer("tanhshrink")
Softsign = _layer("softsign")


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, self.approximate)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()

    def forward(self, x):
        return F.softplus(x)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self.weight = self.create_parameter(
            [num_parameters], weight_attr, default_initializer=I.Constant(init)
        )

    def forward(self, x):
        return F.prelu(x, self.weight)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)
