"""Common layers (reference: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["Linear", "Embedding", "Dropout", "Dropout2D", "AlphaDropout",
           "Flatten", "Pad1D", "Pad2D", "Upsample", "UpsamplingBilinear2D",
           "UpsamplingNearest2D", "Bilinear", "CosineSimilarity", "Unfold"]


class Linear(Layer):
    """y = xW + b with W [in_features, out_features] (reference:
    python/paddle/nn/layer/common.py Linear; kernel dispatch via matmul on MXU)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr, is_bias=False
        )
        self.bias = self.create_parameter(
            shape=[out_features], attr=bias_attr, is_bias=True
        )

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    """reference: python/paddle/nn/layer/common.py Embedding."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        from paddle_tpu.nn import initializer as I

        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0),
        )
        if padding_idx is not None:
            self.weight._set_value(self.weight._value.at[padding_idx].set(0.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training, mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from paddle_tpu.ops.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value, data_format=self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value, data_format=self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False,
                 align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor, self.mode = size, scale_factor, mode
        self.align_corners = align_corners

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode=self.mode, align_corners=self.align_corners)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, mode="bilinear", align_corners=True)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, mode="nearest")


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[out_features, in1_features, in2_features], attr=weight_attr
        )
        self.bias = self.create_parameter(shape=[1, out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Unflatten(Layer):
    """reference nn/layer/common.py Unflatten."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from paddle_tpu.ops.extras import unflatten

        return unflatten(x, self.axis, self.shape)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from paddle_tpu.nn.functional import paddle_pairwise_distance

        out = paddle_pairwise_distance(x, y, self.p, self.epsilon)
        if self.keepdim:
            out = out.unsqueeze(-1)
        return out


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return F.pixel_shuffle(x, self.upscale_factor)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups, self.data_format = groups, data_format

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return F.channel_shuffle(x, self.groups, self.data_format)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return F.fold(x, *self.args)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.output_size = output_size

    def forward(self, x, indices):
        import paddle_tpu.nn.functional as F

        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size)


class Softmax2D(Layer):
    """Softmax over channels of NCHW maps (reference nn Softmax2D)."""

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return F.softmax(x, axis=-3)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return F.dropout(x, self.p, axis=(0, 1), training=self.training)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding

    def forward(self, x):
        from paddle_tpu.ops.manipulation import pad as _pad

        p = self.padding
        if isinstance(p, int):
            p = [p, p, p, p]
        # paddle pad2d order: [left, right, top, bottom]
        return _pad(x, [0, 0, 0, 0, p[2], p[3], p[0], p[1]])


class LpPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        return F.lp_pool2d(x, *self.args)


__all__ += ["Unflatten", "PairwiseDistance", "PixelShuffle", "PixelUnshuffle",
            "ChannelShuffle", "Fold", "MaxUnPool2D", "Softmax2D", "Dropout3D",
            "ZeroPad2D", "LpPool2D"]
