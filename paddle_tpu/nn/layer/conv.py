"""Convolution layers (reference: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose"]


def _pair(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(self, nd, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        self._nd = nd
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _pair(kernel_size, nd)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        fan_in = in_channels * int(__import__("numpy").prod(self._kernel_size)) // groups
        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, *self._kernel_size],
            attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in, negative_slope=0.0, nonlinearity="relu"),
        )
        self.bias = self.create_parameter(shape=[out_channels], attr=bias_attr, is_bias=True)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, kernel_size={self._kernel_size}, "
                f"stride={self._stride}, padding={self._padding}")


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        ks = _pair(kernel_size, 2)
        self._args = (stride, padding, output_padding, dilation, groups)
        self.weight = self.create_parameter(
            shape=[in_channels, out_channels // groups, *ks], attr=weight_attr,
            default_initializer=I.XavierUniform(),
        )
        self.bias = self.create_parameter(shape=[out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, output_size=None):
        stride, padding, output_padding, dilation, groups = self._args
        return F.conv2d_transpose(x, self.weight, self.bias, stride, padding,
                                  output_padding, dilation, groups, output_size)


class Conv1DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        ks = _pair(kernel_size, 1)
        self._args = (stride, padding, output_padding, dilation, groups)
        self.weight = self.create_parameter(
            shape=[in_channels, out_channels // groups, *ks], attr=weight_attr,
            default_initializer=I.XavierUniform(),
        )
        self.bias = self.create_parameter(shape=[out_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x, output_size=None):
        stride, padding, output_padding, dilation, groups = self._args
        return F.conv1d_transpose(x, self.weight, self.bias, stride, padding,
                                  output_padding, dilation, groups, output_size)


class Conv3DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__()
        ks = _pair(kernel_size, 3)
        self._args = (stride, padding, output_padding, dilation, groups)
        self.weight = self.create_parameter(
            shape=[in_channels, out_channels // groups, *ks], attr=weight_attr,
            default_initializer=I.XavierUniform(),
        )
        self.bias = self.create_parameter(shape=[out_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x, output_size=None):
        stride, padding, output_padding, dilation, groups = self._args
        return F.conv3d_transpose(x, self.weight, self.bias, stride, padding,
                                  output_padding, dilation, groups, output_size)


__all__ += ["Conv1DTranspose", "Conv3DTranspose"]
