"""Layer base class (reference: python/paddle/nn/layer/layers.py `Layer`).

Parameters are Tensors with stop_gradient=False; sublayers auto-register via
__setattr__. state_dict round-trips through paddle_tpu.framework.io_.
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Callable, Iterator

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dtype import get_default_dtype, to_jax_dtype
from paddle_tpu.core.tensor import Tensor

__all__ = ["Layer", "Parameter", "Sequential", "LayerList", "ParameterList", "LayerDict", "Identity"]


class Parameter(Tensor):
    """Trainable tensor (reference: EagerParamBase, base/framework.py)."""

    def __init__(self, value, trainable: bool = True, name: str | None = None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.persistable = True

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter " + super().__repr__()


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        self._dtype = dtype
        self.training = True
        self._forward_pre_hooks: OrderedDict = OrderedDict()
        self._forward_post_hooks: OrderedDict = OrderedDict()
        self._casted_dtype = None

    # ---- registration -----------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        bufs = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if subs is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            subs[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                else:
                    params[name] = value
                    return
            if subs is not None and name in subs and value is None:
                del subs[name]
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ---- parameter creation ----------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from paddle_tpu.nn import initializer as I

        dtype = dtype or self._dtype or get_default_dtype()
        init = None
        name = None
        trainable = True
        if attr is not None and attr is not False:
            init = getattr(attr, "initializer", None)
            name = getattr(attr, "name", None)
            trainable = getattr(attr, "trainable", True)
        if attr is False:
            return None
        if init is None:
            init = default_initializer or (I.Constant(0.0) if is_bias else I.XavierUniform())
        value = init(shape, to_jax_dtype(dtype))
        return Parameter(value, trainable=trainable, name=name)

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(jnp.asarray(tensor))
        if tensor is not None:
            tensor.persistable = persistable
        self._buffers[name] = tensor
        return tensor

    # ---- traversal --------------------------------------------------------
    def named_sublayers(self, prefix="", include_self=False) -> Iterator:
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield p, sub
            yield from sub.named_sublayers(prefix=p)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator:
        seen = set()
        for name, p in self._parameters.items():
            if p is None or id(p) in seen:
                continue
            seen.add(id(p))
            yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for lname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sp = f"{prefix}.{lname}" if prefix else lname
                for n, p in sub.named_parameters(prefix=sp):
                    if id(p) in seen:
                        continue
                    seen.add(id(p))
                    yield n, p

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is None:
                continue
            yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sp = f"{prefix}.{lname}" if prefix else lname
                yield from sub.named_buffers(prefix=sp)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    # ---- modes ------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn: Callable):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # ---- state dict -------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True):
        out = destination if destination is not None else OrderedDict()
        for n, p in self.named_parameters(include_sublayers=include_sublayers):
            out[n] = p
        for n, b in self.named_buffers(include_sublayers=include_sublayers):
            if b.persistable:
                out[n] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            val = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            tgt = own[k]
            if tuple(val.shape) != tuple(tgt._value.shape):
                raise ValueError(f"shape mismatch for '{k}': {val.shape} vs {tgt._value.shape}")
            tgt._set_value(val.astype(tgt._value.dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = to_jax_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p._value.dtype, np.floating):
                    p._set_value(p._value.astype(d))
            for b in self.buffers():
                if jnp.issubdtype(b._value.dtype, np.floating):
                    b._set_value(b._value.astype(d))
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ---- hooks ------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        key = len(self._forward_pre_hooks)
        self._forward_pre_hooks[key] = hook
        return _HookHandle(self._forward_pre_hooks, key)

    def register_forward_post_hook(self, hook):
        key = len(self._forward_post_hooks)
        self._forward_post_hooks[key] = hook
        return _HookHandle(self._forward_post_hooks, key)

    # ---- call -------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, args)
            if res is not None:
                args = res if isinstance(res, tuple) else (res,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, args, out)
            if res is not None:
                out = res
        return out

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{type(self).__name__}({extra}"]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        return "\n".join(lines) + ")"

    def full_name(self):
        return type(self).__name__.lower()

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


class _HookHandle:
    def __init__(self, store, key):
        self._store = store
        self._key = key

    def remove(self):
        self._store.pop(self._key, None)


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and not isinstance(layers[0], Layer):
            layers = layers[0]
        if len(layers) and isinstance(layers[0], tuple) and not isinstance(layers[0], Layer):
            for name, layer in layers:
                self.add_sublayer(str(name), layer)
        else:
            for i, layer in enumerate(layers):
                self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        vals = list(self._sub_layers.values())
        if isinstance(idx, slice):
            return Sequential(*vals[idx])
        return vals[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self

    def insert(self, index, layer):
        vals = list(self._sub_layers.values())
        vals.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(vals):
            self._sub_layers[str(i)] = l

    def __getitem__(self, idx):
        vals = list(self._sub_layers.values())
        if isinstance(idx, slice):
            return LayerList(vals[idx])
        return vals[idx]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            for k, v in (sublayers.items() if isinstance(sublayers, dict) else sublayers):
                self.add_sublayer(k, v)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __len__(self):
        return len(self._sub_layers)

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()


class Identity(Layer):
    def forward(self, x):
        return x
