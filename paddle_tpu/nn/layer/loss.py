"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
           "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "MarginRankingLoss",
           "CosineEmbeddingLoss", "TripletMarginLoss", "HingeEmbeddingLoss"]


class CrossEntropyLoss(Layer):
    """`use_fused=None` defers to the `use_fused_cross_entropy` flag: hard-
    label softmax CE then runs the chunked fused kernel (no [N, C]
    log-softmax materialized; see docs/fused_head_cross_entropy.md)."""

    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                 use_fused=None, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing
        self.use_fused = use_fused

    def forward(self, input, label):
        return F.cross_entropy(
            input, label, weight=self.weight, ignore_index=self.ignore_index,
            reduction=self.reduction, soft_label=self.soft_label, axis=self.axis,
            use_softmax=self.use_softmax, label_smoothing=self.label_smoothing,
            use_fused=self.use_fused,
        )


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = weight, ignore_index, reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight, self.reduction, self.pos_weight = weight, reduction, pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight
        )


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        m, p, e, s, r = self.args
        return F.triplet_margin_loss(input, positive, negative, m, p, e, s, r)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CTCLoss(Layer):
    """reference nn/layer/loss.py CTCLoss over F.ctc_loss."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, logits, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(logits, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class HuberLoss(Layer):
    def __init__(self, delta=1.0, reduction="mean", name=None):
        super().__init__()
        self.delta, self.reduction = delta, reduction

    def forward(self, input, label):
        return F.huber_loss(input, label, delta=self.delta,
                            reduction=self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.epsilon, self.reduction = epsilon, reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, self.log_input, self.full,
                                  self.epsilon, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


__all__ += ["CTCLoss", "HuberLoss", "GaussianNLLLoss", "PoissonNLLLoss",
            "MultiLabelSoftMarginLoss", "SoftMarginLoss",
            "TripletMarginWithDistanceLoss"]


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax (reference nn/layer/loss.py AdaptiveLogSoftmaxWithLoss;
    Grave et al.): frequent words get full-size logits from the head, rare
    words route through down-projected tail clusters.

    TPU note: every token computes all clusters (dense compute, masked
    select) — data-dependent gather/scatter of the reference's CUDA path
    would break XLA's static shapes, and head+tail are skinny matmuls the
    MXU does at negligible cost vs the vocabulary savings in memory."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        from paddle_tpu.nn.layer.common import Linear

        cutoffs = list(cutoffs)
        if (not cutoffs or cutoffs != sorted(cutoffs)
                or any(c <= 0 or c >= n_classes - 1 for c in cutoffs)
                or len(set(cutoffs)) != len(cutoffs)):
            raise ValueError("cutoffs must be unique, ascending, in (0, n_classes-1)")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.cutoffs[0] + self.n_clusters
        self.head = Linear(in_features, self.head_size, bias_attr=head_bias)
        self.tail = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = Sequential_(
                Linear(in_features, hsz, bias_attr=False),
                Linear(hsz, osz, bias_attr=False),
            )
            self.tail.append(proj)
            self.add_sublayer(f"tail_{i}", proj)

    def _full_log_prob(self, input):
        import jax.numpy as jnp

        from paddle_tpu.core.tensor import apply_op

        head_out = self.head(input)
        tails = [t(input) for t in self.tail]

        def f(h, *ts):
            import jax

            head_lp = jax.nn.log_softmax(h, axis=-1)
            shortlist = head_lp[..., : self.cutoffs[0]]
            parts = [shortlist]
            for i, tv in enumerate(ts):
                cluster_lp = jax.nn.log_softmax(tv, axis=-1)
                gate = head_lp[..., self.cutoffs[0] + i: self.cutoffs[0] + i + 1]
                parts.append(gate + cluster_lp)
            return jnp.concatenate(parts, axis=-1)

        return apply_op(f, head_out, *tails, name="adaptive_log_softmax")

    def log_prob(self, input):
        return self._full_log_prob(input)

    def predict(self, input):
        lp = self._full_log_prob(input)
        from paddle_tpu.ops.reduction import argmax

        return argmax(lp, axis=-1)

    def forward(self, input, label):
        import jax.numpy as jnp

        from paddle_tpu.core.tensor import apply_op

        lp = self._full_log_prob(input)

        def f(l, y):
            picked = jnp.take_along_axis(l, y[..., None].astype(jnp.int32),
                                         axis=-1)[..., 0]
            return -picked, -picked.mean()

        out, loss = apply_op(f, lp, label, name="adaptive_nll")
        return out, loss


from paddle_tpu.nn.layer.layers import Sequential as Sequential_  # noqa: E402

__all__ += ["AdaptiveLogSoftmaxWithLoss"]


class RNNTLoss(Layer):
    """reference nn RNNTLoss over F.rnnt_loss (warp-transducer analog)."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)


__all__ += ["RNNTLoss"]
