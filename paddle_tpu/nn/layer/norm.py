"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["LayerNorm", "RMSNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
           "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm2D",
           "LocalResponseNorm"]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = (
            None if weight_attr is False
            else self.create_parameter(self._normalized_shape, weight_attr,
                                       default_initializer=I.Constant(1.0))
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter(self._normalized_shape, bias_attr, is_bias=True)
        )

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """LLaMA-family RMSNorm; fused on TPU (Pallas kernel in ops/pallas)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], weight_attr,
                                            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._use_global_stats = use_global_stats
        self.weight = (
            None if weight_attr is False
            else self.create_parameter([num_features], weight_attr,
                                       default_initializer=I.Constant(1.0))
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter([num_features], bias_attr, is_bias=True)
        )
        self.register_buffer("_mean", jnp.zeros(num_features, jnp.float32))
        self.register_buffer("_variance", jnp.ones(num_features, jnp.float32))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batchnorm. Under jit+shard_map the mean/var reduce over the
    data axis automatically via psum (see distributed.fleet); eagerly on a single
    chip it equals BatchNorm (reference: python/paddle/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        from paddle_tpu.nn.layer.layers import Layer as _L

        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon)
            new.weight = layer.weight
            new.bias = layer.bias
            new._buffers = layer._buffers
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.weight = (
            None if weight_attr is False
            else self.create_parameter([num_channels], weight_attr,
                                       default_initializer=I.Constant(1.0))
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter([num_channels], bias_attr, is_bias=True)
        )

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.scale = (
            None if weight_attr is False
            else self.create_parameter([num_features], weight_attr,
                                       default_initializer=I.Constant(1.0))
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter([num_features], bias_attr, is_bias=True)
        )

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias, eps=self._epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class InstanceNorm1D(InstanceNorm2D):
    """NCL instance norm (F.instance_norm is rank-agnostic)."""


class InstanceNorm3D(InstanceNorm2D):
    """NCDHW instance norm."""


class SpectralNorm(Layer):
    """reference nn/layer/norm.py SpectralNorm: forward(weight) returns the
    spectrally-normalized weight via persistent power-iteration vectors."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        import numpy as _np

        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        rs = _np.random.RandomState(0)
        self.register_buffer("weight_u", jnp.asarray(
            rs.randn(h).astype(_np.float32)))
        self.register_buffer("weight_v", jnp.asarray(
            rs.randn(w).astype(_np.float32)))

    def forward(self, weight):
        from paddle_tpu.core.tensor import apply_op

        dim, iters, eps = self._dim, self._power_iters, self._epsilon

        def f(wv, u, v):
            m = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
            for _ in range(iters):
                v = m.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = m @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ m @ v
            return wv / sigma, u, v

        out, u_new, v_new = apply_op(f, weight, self.weight_u, self.weight_v,
                                     name="spectral_norm")
        self.weight_u._set_value(u_new.detach()._value)
        self.weight_v._set_value(v_new.detach()._value)
        return out


__all__ += ["InstanceNorm1D", "InstanceNorm3D", "SpectralNorm"]
