"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["MaxPool1D", "MaxPool2D", "AvgPool1D", "AvgPool2D",
           "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveMaxPool2D"]


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, return_mask, data_format)

    def forward(self, x):
        k, s, p, cm, rm, df = self.args
        return F.max_pool2d(x, k, s, p, ceil_mode=cm, return_mask=rm, data_format=df)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, return_mask)

    def forward(self, x):
        k, s, p, cm, rm = self.args
        return F.max_pool1d(x, k, s, p, ceil_mode=cm, return_mask=rm)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive)

    def forward(self, x):
        k, s, p, cm, ex = self.args
        return F.avg_pool2d(x, k, s, p, ceil_mode=cm, exclusive=ex)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, exclusive, ceil_mode)

    def forward(self, x):
        return F.avg_pool1d(x, *self.args)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size,
                                     data_format=self.data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size,
                                     return_mask=self.return_mask)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, return_mask,
                     data_format)

    def forward(self, x):
        k, s, p, cm, rm, df = self.args
        return F.max_pool3d(x, k, s, p, ceil_mode=cm, return_mask=rm,
                            data_format=df)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive)

    def forward(self, x):
        return F.avg_pool3d(x, *self.args)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


__all__ += ["MaxPool3D", "AvgPool3D", "AdaptiveAvgPool3D"]
