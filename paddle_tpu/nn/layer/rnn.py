"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

TPU-native: time recursion is expressed with `jax.lax.scan` so XLA compiles one
fused loop (no Python-level unrolling); gate matmuls are batched onto the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, apply_op
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer, LayerList

__all__ = ["SimpleRNN", "LSTM", "GRU", "LSTMCell", "GRUCell", "SimpleRNNCell", "RNN"]


class _RNNCellBase(Layer):
    def __init__(self, input_size, hidden_size, n_gates, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / (hidden_size ** 0.5)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([n_gates * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([n_gates * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([n_gates * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([n_gates * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)


class SimpleRNNCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", **kwargs):
        super().__init__(input_size, hidden_size, 1, **kwargs)
        self.activation = activation

    def forward(self, inputs, states=None):
        h = states if states is not None else Tensor(
            jnp.zeros((inputs.shape[0], self.hidden_size), inputs._value.dtype))
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        out = apply_op(
            lambda x, hp, wi, wh, bi, bh: act(x @ wi.T + bi + hp @ wh.T + bh),
            inputs, h, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh,
            name="rnn_cell",
        )
        return out, out


class LSTMCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, **kwargs):
        super().__init__(input_size, hidden_size, 4, **kwargs)

    def forward(self, inputs, states=None):
        if states is None:
            z = Tensor(jnp.zeros((inputs.shape[0], self.hidden_size), inputs._value.dtype))
            states = (z, z.clone())
        h, c = states

        def f(x, hp, cp, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hp @ wh.T + bh
            i, fgt, g, o = jnp.split(gates, 4, axis=-1)
            i, fgt, o = jax.nn.sigmoid(i), jax.nn.sigmoid(fgt), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            cn = fgt * cp + i * g
            hn = o * jnp.tanh(cn)
            return hn, cn

        hn, cn = apply_op(f, inputs, h, c, self.weight_ih, self.weight_hh,
                          self.bias_ih, self.bias_hh, name="lstm_cell")
        return hn, (hn, cn)


class GRUCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, **kwargs):
        super().__init__(input_size, hidden_size, 3, **kwargs)

    def forward(self, inputs, states=None):
        h = states if states is not None else Tensor(
            jnp.zeros((inputs.shape[0], self.hidden_size), inputs._value.dtype))

        def f(x, hp, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = hp @ wh.T + bh
            ir, iz, ig = jnp.split(gi, 3, axis=-1)
            hr, hz, hg = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            g = jnp.tanh(ig + r * hg)
            return (1 - z) * g + z * hp

        hn = apply_op(f, inputs, h, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh, name="gru_cell")
        return hn, hn


class RNN(Layer):
    """Generic scanner over a cell (reference: nn/layer/rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # eager scan in Python (clear + differentiable); jit path compiles whole loop
        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        steps = x.shape[0]
        rng = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        outs = []
        state = initial_states
        for i in rng:
            out, state = self.cell(x[i], state)
            outs.append(out)
        if self.is_reverse:
            outs.reverse()
        from paddle_tpu.ops.manipulation import stack

        y = stack(outs, axis=0)
        if not self.time_major:
            y = y.transpose([1, 0, 2])
        return y, state


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__()
        self.mode = mode
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.bidirectional = direction in ("bidirect", "bidirectional")
        cell_cls = {"LSTM": LSTMCell, "GRU": GRUCell, "RNN_TANH": SimpleRNNCell}[mode]
        self.fw = LayerList()
        self.bw = LayerList() if self.bidirectional else None
        for l in range(num_layers):
            isz = input_size if l == 0 else hidden_size * (2 if self.bidirectional else 1)
            self.fw.append(RNN(cell_cls(isz, hidden_size), time_major=True))
            if self.bidirectional:
                self.bw.append(RNN(cell_cls(isz, hidden_size), is_reverse=True, time_major=True))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from paddle_tpu.ops.manipulation import concat

        x = inputs if self.time_major else inputs.transpose([1, 0, 2])
        final_states = []
        for l in range(self.num_layers):
            yf, sf = self.fw[l](x)
            if self.bidirectional:
                yb, sb = self.bw[l](x)
                x = concat([yf, yb], axis=-1)
                final_states.append((sf, sb))
            else:
                x = yf
                final_states.append(sf)
        y = x if self.time_major else x.transpose([1, 0, 2])
        return y, final_states


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        super().__init__("RNN_TANH", input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


RNNCellBase = _RNNCellBase  # reference public name (nn/layer/rnn.py RNNCellBase)


class BiRNN(Layer):
    """Bidirectional wrapper over two cells (reference nn/layer/rnn.py
    BiRNN): forward and reverse scans concatenated on the feature axis."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        y_fw, s_fw = self.rnn_fw(inputs, st_fw, sequence_length)
        y_bw, s_bw = self.rnn_bw(inputs, st_bw, sequence_length)
        from paddle_tpu.ops.manipulation import concat

        return concat([y_fw, y_bw], axis=-1), (s_fw, s_bw)


__all__ += ["RNNCellBase", "BiRNN"]
