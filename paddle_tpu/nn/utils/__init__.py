"""paddle.nn.utils (reference: python/paddle/nn/utils/ — weight_norm_hook.py,
spectral_norm_hook.py, transform_parameters.py, clip_grad_norm_.py).

Reparameterizations install a forward-pre-hook that recomputes the weight
from the decomposed parameters before every call — the same mechanism as the
reference's hook objects; the recompute is a couple of elementwise/matmul ops
that XLA fuses into the layer's own program.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor, apply_op
from paddle_tpu.nn.clip import clip_grad_norm_  # noqa: F401  (re-export)

__all__ = [
    "weight_norm", "remove_weight_norm", "spectral_norm",
    "parameters_to_vector", "vector_to_parameters", "clip_grad_norm_",
    "clip_grad_value_",
]


def _norm_except_dim(v, dim):
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


def weight_norm(layer, name: str = "weight", dim: int = 0):
    """w = g * v / ||v||  (reference weight_norm_hook.py WeightNorm.apply)."""
    w = getattr(layer, name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    dim = dim if dim >= 0 else w._value.ndim + dim
    g = Tensor(np.asarray(_norm_except_dim(w._value, dim)), stop_gradient=False)
    v = Tensor(w._value, stop_gradient=False)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    # the composed weight is derived state, not a trainable parameter
    if name in layer._parameters:
        del layer._parameters[name]

    def recompute(lyr, inputs):
        gg = getattr(lyr, name + "_g")
        vv = getattr(lyr, name + "_v")
        w_new = apply_op(
            lambda gv, vv_: gv * vv_ / (_norm_except_dim(vv_, dim) + 1e-12),
            gg, vv, name="weight_norm")
        object.__setattr__(lyr, name, w_new)
        return None

    handle = layer.register_forward_pre_hook(recompute)
    # per-parameter-name state: a layer may have several weight-normed params
    if not hasattr(layer, "_weight_norm_handles"):
        layer._weight_norm_handles = {}
        layer._weight_norm_dims = {}
    layer._weight_norm_handles[name] = handle
    layer._weight_norm_dims[name] = dim
    recompute(layer, None)
    return layer


def remove_weight_norm(layer, name: str = "weight"):
    handle = getattr(layer, "_weight_norm_handles", {}).pop(name, None)
    if handle is not None:
        handle.remove()
    dim = getattr(layer, "_weight_norm_dims", {}).pop(name, 0)
    g = getattr(layer, name + "_g")
    v = getattr(layer, name + "_v")
    w = Tensor(np.asarray(
        g._value * v._value / (_norm_except_dim(v._value, dim) + 1e-12)),
        stop_gradient=False)
    for pname in (name + "_g", name + "_v"):
        if pname in layer._parameters:
            del layer._parameters[pname]
        if hasattr(layer, pname):
            object.__delattr__(layer, pname)
    # weight_norm's pre-hook set the composed weight as a plain instance
    # attribute; drop it so the re-registered parameter isn't shadowed and
    # forward / state_dict / the optimizer all see the same tensor
    if name in layer.__dict__:
        object.__delattr__(layer, name)
    layer.add_parameter(name, w)
    return layer


def spectral_norm(layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim: int | None = None):
    """W_sn = W / sigma_max(W) via power iteration on persistent u/v buffers
    (reference spectral_norm_hook.py SpectralNorm)."""
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    mat = np.moveaxis(np.asarray(w._value), dim, 0)
    h = mat.shape[0]
    wdth = int(np.prod(mat.shape[1:])) if mat.ndim > 1 else 1
    rs = np.random.RandomState(0)
    layer.register_buffer(name + "_u", jnp.asarray(
        rs.randn(h).astype(np.asarray(w._value).dtype)))
    layer.register_buffer(name + "_v", jnp.asarray(
        rs.randn(wdth).astype(np.asarray(w._value).dtype)))
    orig = Tensor(w._value, stop_gradient=False)
    layer.add_parameter(name + "_orig", orig)
    if name in layer._parameters:
        del layer._parameters[name]

    def recompute(lyr, inputs):
        w0 = getattr(lyr, name + "_orig")
        u = getattr(lyr, name + "_u")
        v = getattr(lyr, name + "_v")

        def f(wv, uv, vv):
            m = jnp.moveaxis(wv, dim, 0).reshape(h, -1)
            for _ in range(n_power_iterations):
                vv = m.T @ uv
                vv = vv / (jnp.linalg.norm(vv) + eps)
                uv = m @ vv
                uv = uv / (jnp.linalg.norm(uv) + eps)
            sigma = uv @ m @ vv
            return wv / sigma, uv, vv

        w_sn, u_new, v_new = apply_op(f, w0, u, v, name="spectral_norm")
        u._set_value(u_new.detach()._value)
        v._set_value(v_new.detach()._value)
        object.__setattr__(lyr, name, w_sn)
        return None

    layer.register_forward_pre_hook(recompute)
    recompute(layer, None)
    return layer


def parameters_to_vector(parameters, name=None):
    ps = list(parameters)
    return apply_op(lambda *vs: jnp.concatenate([v.reshape(-1) for v in vs]),
                    *ps, name="parameters_to_vector")


def vector_to_parameters(vec, parameters, name=None):
    ps = list(parameters)
    flat = np.asarray(vec._value if isinstance(vec, Tensor) else vec)
    off = 0
    for p in ps:
        n = int(np.prod(p._value.shape)) if p._value.ndim else 1
        p._set_value(jnp.asarray(flat[off:off + n]).reshape(p._value.shape)
                     .astype(p._value.dtype))
        off += n
    if off != flat.size:
        raise ValueError(f"vector has {flat.size} elements; parameters need {off}")
    return ps


def clip_grad_value_(parameters, clip_value):
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    cv = float(clip_value)
    for p in params:
        if p.grad is not None:
            p.grad._set_value(jnp.clip(p.grad._value, -cv, cv))
    return params
