"""nn.utils (reference: python/paddle/nn/utils)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["parameters_to_vector", "vector_to_parameters"]


def parameters_to_vector(parameters):
    vals = [p._value.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(vals))


def vector_to_parameters(vec, parameters):
    off = 0
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    for p in parameters:
        n = p.size
        p._set_value(v[off : off + n].reshape(p._value.shape).astype(p._value.dtype))
        off += n
