"""paddle_tpu.observability — the unified observability plane.

One instrument for every subsystem (docs/observability.md):

* `metrics`  — process-wide registry of labeled counters/gauges/
  histograms with lock-striped updates, scrape-time collectors,
  snapshot(), Prometheus text exposition (served as ``GET /metrics`` on
  the serve.py chassis) and JSONL export through `utils.LogWriter`.
* `tracing`  — cross-component spans carrying a trace id that propagates
  router -> replica -> engine -> scheduler -> decode step and training-
  step phase spans, exported (merged with optional `jax.profiler` device
  traces) as one Chrome/Perfetto file.
* `events`   — the structured event journal: one schema for resilience/
  serving lifecycle events (rollback, quarantine, failover, breaker
  transitions, page eviction, drain), ring-buffered + optional JSONL.

Training-side honest telemetry (per-step loss / grad-norm / skip flags /
fp8 amax, MFU from ``compiled.cost_analysis()`` FLOPs) lives on
`parallel.CompiledTrainStep(collect_metrics=True)` and streams through
`hapi.MetricsCallback` into all three surfaces.
"""
from paddle_tpu.observability import events, metrics, tracing  # noqa: F401
from paddle_tpu.observability.events import EventJournal, journal
from paddle_tpu.observability.metrics import (Counter, Gauge, Histogram,
                                              MetricsRegistry, registry)
from paddle_tpu.observability.tracing import (current_trace_id,
                                              export_chrome, new_trace_id,
                                              span, start_tracing,
                                              stop_tracing, trace_context,
                                              tracing_active)

__all__ = [
    "metrics", "tracing", "events",
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "registry",
    "span", "start_tracing", "stop_tracing", "tracing_active",
    "trace_context", "current_trace_id", "new_trace_id", "export_chrome",
    "EventJournal", "journal",
]
