"""Structured event journal — ONE schema for lifecycle events.

Every resilience/serving lifecycle transition (rollback, quarantine,
failover, circuit-breaker open/close, page eviction, drain, replica
death, checkpoint commit) lands here as one record:

    {"ts": <epoch s>, "component": "router|serving|resilience|ckpt|...",
     "event": "<snake_case name>", "severity": "info|warn|error",
     ...event-specific fields}

The journal is a bounded in-memory ring (`recent()` is the operator's
post-mortem view and what tests assert on) plus optional durable sinks:
`attach(path)` appends JSONL (flushed per event — the log must survive
the crash it describes), `attach(LogWriter)` streams through the
VisualDL-analog event log. Every emit also increments the
``events_total{component,event}`` counter in the metrics registry, so
/metrics exposes event RATES without reading the journal.

`paddle_tpu.distributed.resilience.supervisor.IncidentLog` bridges its
incidents in here automatically — one plane, not two.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque

__all__ = ["EventJournal", "journal", "emit"]

SCHEMA_FIELDS = ("ts", "component", "event", "severity")
SEVERITIES = ("info", "warn", "error")


class EventJournal:
    def __init__(self, maxlen: int = 4096):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=maxlen)
        self._files: dict[str, object] = {}      # path -> open file
        self._writers: list = []                 # LogWriter-likes
        self.emitted = 0
        self.sink_errors: list[str] = []

    def _sink_error(self, sink, e):
        # a broken sink (full disk, closed writer) must never crash the
        # EMITTER — journal emits sit on recovery paths (rollback) and
        # under component locks; record the failure and keep going
        import warnings

        msg = f"{type(e).__name__}: {e}"
        with self._lock:
            first = not self.sink_errors
            self.sink_errors.append(msg)
        if first:
            warnings.warn(f"event-journal sink failed ({msg}); events keep "
                          f"landing in the in-memory ring")

    def emit(self, component: str, event: str, severity: str = "info",
             **fields) -> dict:
        if severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}, "
                             f"got {severity!r}")
        for k in SCHEMA_FIELDS:
            if k in fields:
                raise ValueError(f"field {k!r} is part of the schema")
        rec = {"ts": round(time.time(), 3), "component": str(component),
               "event": str(event), "severity": severity, **fields}
        with self._lock:
            self._ring.append(rec)
            self.emitted += 1
            files = list(self._files.values())
            writers = list(self._writers)
        for f in files:
            try:
                f.write(json.dumps(rec, default=str) + "\n")
                f.flush()
            except (OSError, ValueError) as e:
                self._sink_error(f, e)
        for w in writers:
            try:
                w.add_text(f"events/{component}/{event}",
                           json.dumps(rec, default=str))
            except (OSError, ValueError) as e:
                self._sink_error(w, e)
        # event RATES ride the metrics plane (lazy import: metrics is
        # dependency-free, but keep the journal usable standalone)
        from paddle_tpu.observability import metrics as _m

        _m.registry().counter(
            "events_total", "structured journal events emitted",
            labels=("component", "event")).labels(
            component=component, event=event).inc()
        return rec

    def attach(self, sink):
        """`sink`: a filesystem path (JSONL, append, flushed per event) or
        a LogWriter-like with add_text()."""
        if isinstance(sink, str):
            with self._lock:
                if sink not in self._files:
                    self._files[sink] = open(sink, "a")
        else:
            with self._lock:
                self._writers.append(sink)

    def detach(self, sink):
        with self._lock:
            if isinstance(sink, str):
                f = self._files.pop(sink, None)
                if f is not None:
                    f.close()
            elif sink in self._writers:
                self._writers.remove(sink)

    def recent(self, n: int | None = None, component: str | None = None,
               event: str | None = None) -> list:
        with self._lock:
            recs = list(self._ring)
        if component is not None:
            recs = [r for r in recs if r["component"] == component]
        if event is not None:
            recs = [r for r in recs if r["event"] == event]
        return recs[-n:] if n else recs

    def clear(self):
        with self._lock:
            self._ring.clear()
            self.emitted = 0

    def close(self):
        with self._lock:
            for f in self._files.values():
                f.close()
            self._files.clear()
            self._writers.clear()


_default = EventJournal()


def journal() -> EventJournal:
    """The process-wide journal every component emits through."""
    return _default


def emit(component: str, event: str, severity: str = "info", **fields):
    """Shorthand for `journal().emit(...)` — the one-liner components
    call at lifecycle transitions."""
    return _default.emit(component, event, severity=severity, **fields)
