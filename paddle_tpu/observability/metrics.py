"""Process-wide metrics registry — labeled counters / gauges / histograms.

Reference context: the reference framework's observability surface is
VisualDL + ad-hoc per-module stat dicts; production TPU fleets scrape
Prometheus. This registry is the ONE place every component reports through
(docs/observability.md):

  * **cheap updates** — child handles (`counter(...).labels(...)`) cache
    their value slot; updates take one striped lock (16 stripes keyed by
    the child's label hash), so concurrent decode/feeder/router threads
    never serialize on a single registry lock;
  * **collectors** — components that already keep their own honest
    counters (ServingEngine.stats(), Router.stats()) register a collector
    callback that maps them into gauges/counters AT SCRAPE TIME, so the
    hot path pays nothing. Collectors are owner-weakref'd: a dead engine's
    collector unregisters itself;
  * **snapshot()** — plain nested dicts for programmatic gates
    (bench_regression reads this);
  * **prometheus_text()** — text exposition format 0.0.4, served as
    ``GET /metrics`` by the serve.py chassis;
  * **export_jsonl()** — stream the snapshot into a
    `paddle_tpu.utils.LogWriter` (the VisualDL-analog JSONL event log).

The process-wide default lives behind `registry()`; tests isolate with
`MetricsRegistry()` instances or `registry().reset()`.
"""
from __future__ import annotations

import json
import math
import threading
import weakref

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram", "registry"]

_N_STRIPES = 16
_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                    50.0, 100.0, 500.0, 1000.0, 5000.0)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _escape(v) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v) -> str:
    """HELP-line escaping per exposition format 0.0.4: ONLY backslash and
    newline (quotes stay literal — the label-value escaper would garble
    them in Prometheus/Grafana UIs)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Exposition-format number: integral values print without the trailing
    .0 (golden-test stable), non-finite as +Inf/-Inf/NaN."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Family:
    """One named metric family: children per label set. Label NAMES are
    fixed at registration; children are created on first `.labels()`."""

    kind = "untyped"

    def __init__(self, reg: "MetricsRegistry", name: str, help: str,
                 label_names: tuple):
        self._reg = reg
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._children: dict[tuple, object] = {}

    def labels(self, **labels):
        if tuple(sorted(labels)) != tuple(sorted(self.label_names)):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.label_names)}")
        key = _label_key(labels)
        child = self._children.get(key)
        if child is None:
            with self._reg._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make_child(self._reg._stripe(key))
                    self._children[key] = child
        return child

    def _default_child(self):
        if self.label_names:
            raise ValueError(
                f"{self.name} declares labels {self.label_names}; use "
                f".labels(...)")
        return self.labels()

    def samples(self):
        """[(label_dict, child)] in stable (sorted label key) order."""
        return [(dict(k), c) for k, c in sorted(self._children.items())]


class _CounterChild:
    __slots__ = ("_lock", "_value")

    def __init__(self, lock):
        self._lock = lock
        self._value = 0.0

    def inc(self, n: float = 1.0):
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    def _set_total(self, v: float):
        """Mirror a monotonic source (e.g. Router.completed) at scrape
        time — collector-only API. A LOWER value is accepted as a source
        reset (engine.reset_stats() between bench arms): standard
        Prometheus counter-reset semantics, which rate() handles."""
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Counter(_Family):
    kind = "counter"

    def _make_child(self, lock):
        return _CounterChild(lock)

    def inc(self, n: float = 1.0):
        self._default_child().inc(n)

    @property
    def value(self) -> float:
        return self._default_child().value


class _GaugeChild:
    __slots__ = ("_lock", "_value")

    def __init__(self, lock):
        self._lock = lock
        self._value = 0.0

    def set(self, v: float):
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0):
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0):
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Family):
    kind = "gauge"

    def _make_child(self, lock):
        return _GaugeChild(lock)

    def set(self, v: float):
        self._default_child().set(v)

    def inc(self, n: float = 1.0):
        self._default_child().inc(n)

    def dec(self, n: float = 1.0):
        self._default_child().dec(n)

    @property
    def value(self) -> float:
        return self._default_child().value


class _HistogramChild:
    __slots__ = ("_lock", "bounds", "counts", "sum", "count")

    def __init__(self, lock, bounds):
        self._lock = lock
        self.bounds = bounds                # ascending, +Inf implicit
        self.counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float):
        v = float(v)
        i = 0
        for i, b in enumerate(self.bounds):
            if v <= b:
                break
        else:
            i = len(self.bounds)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def cumulative(self):
        """[(le, cumulative_count)] including the +Inf bucket."""
        with self._lock:
            counts = list(self.counts)
            total = self.count
        out, acc = [], 0
        for b, c in zip(self.bounds, counts):
            acc += c
            out.append((b, acc))
        out.append((float("inf"), total))
        return out

    def quantile(self, q: float) -> float:
        """Linear-interpolated quantile estimate from the buckets (the
        p99 the bench gates read — honest to bucket resolution)."""
        cum = self.cumulative()
        if not self.count:
            return 0.0
        target = q * self.count
        lo = 0.0
        prev = 0
        for le, acc in cum:
            if acc >= target:
                if math.isinf(le):
                    return lo  # best estimate: the last finite bound
                span = acc - prev
                frac = (target - prev) / span if span else 1.0
                return lo + (le - lo) * frac
            lo, prev = (0.0 if math.isinf(le) else le), acc
        return lo


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, reg, name, help, label_names,
                 buckets=_DEFAULT_BUCKETS):
        super().__init__(reg, name, help, label_names)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")

    def _make_child(self, lock):
        return _HistogramChild(lock, self.buckets)

    def observe(self, v: float):
        self._default_child().observe(v)

    def quantile(self, q: float) -> float:
        return self._default_child().quantile(q)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}
        self._stripes = [threading.Lock() for _ in range(_N_STRIPES)]
        # [(fn, owner_weakref|None)] — owner-dead collectors are dropped
        self._collectors: list = []

    def _stripe(self, key) -> threading.Lock:
        return self._stripes[hash(key) % _N_STRIPES]

    def _get_or_create(self, cls, name, help, labels, **kw):
        fam = self._families.get(name)
        if fam is not None:
            if not isinstance(fam, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {fam.kind}")
            return fam
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(self, name, help, tuple(labels), **kw)
                self._families[name] = fam
            elif not isinstance(fam, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {fam.kind}")
            return fam

    def counter(self, name, help="", labels=()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name, help="", labels=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=(),
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    # -- collectors ----------------------------------------------------------
    def add_collector(self, fn, owner=None):
        """`fn(registry)` runs before every snapshot/exposition. With
        `owner`, the collector lives exactly as long as the owner object
        (weakref) — a closed engine stops being scraped without explicit
        unregistration."""
        ref = weakref.ref(owner) if owner is not None else None
        with self._lock:
            self._collectors.append((fn, ref))

    def run_collectors(self):
        with self._lock:
            entries = list(self._collectors)
        dead = []
        for fn, ref in entries:
            if ref is not None and ref() is None:
                dead.append((fn, ref))
                continue
            fn(self)  # a broken collector should fail loudly, not hide
        if dead:
            with self._lock:
                self._collectors = [e for e in self._collectors
                                    if e not in dead]

    # -- export --------------------------------------------------------------
    def snapshot(self) -> dict:
        """{name: {"type", "help", "samples": [{"labels", ...}]}} — counters
        and gauges carry "value"; histograms carry "sum"/"count"/"buckets"
        ([le, cumulative] pairs) and a convenience "p50"/"p99"."""
        self.run_collectors()
        out = {}
        for name in sorted(self._families):
            fam = self._families[name]
            samples = []
            for labels, child in fam.samples():
                if fam.kind == "histogram":
                    samples.append({
                        "labels": labels, "sum": child.sum,
                        "count": child.count,
                        "buckets": [["+Inf" if math.isinf(le) else le, c]
                                    for le, c in child.cumulative()],
                        "p50": child.quantile(0.50),
                        "p99": child.quantile(0.99)})
                else:
                    samples.append({"labels": labels, "value": child.value})
            out[name] = {"type": fam.kind, "help": fam.help,
                         "samples": samples}
        return out

    def prometheus_text(self) -> str:
        """Text exposition format 0.0.4 (the `GET /metrics` body)."""
        self.run_collectors()
        lines = []
        for name in sorted(self._families):
            fam = self._families[name]
            if fam.help:
                lines.append(f"# HELP {name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {name} {fam.kind}")
            for labels, child in fam.samples():
                base = ",".join(f'{k}="{_escape(v)}"'
                                for k, v in sorted(labels.items()))
                if fam.kind == "histogram":
                    # cumulative buckets, then sum/count (the format's
                    # required order)
                    for le, c in child.cumulative():
                        ls = (base + "," if base else "") + \
                            f'le="{_fmt(le)}"'
                        lines.append(f"{name}_bucket{{{ls}}} {c}")
                    lab = f"{{{base}}}" if base else ""
                    lines.append(f"{name}_sum{lab} {_fmt(child.sum)}")
                    lines.append(f"{name}_count{lab} {child.count}")
                else:
                    lab = f"{{{base}}}" if base else ""
                    lines.append(f"{name}{lab} {_fmt(child.value)}")
        return "\n".join(lines) + "\n"

    def export_jsonl(self, writer, step: int = 0):
        """Write the snapshot through a LogWriter: one scalar event per
        counter/gauge sample (tag = name{labels}) and one text event per
        histogram (the full bucket table as JSON)."""
        snap = self.snapshot()
        for name, fam in snap.items():
            for s in fam["samples"]:
                base = ",".join(f'{k}={v}'
                                for k, v in sorted(s["labels"].items()))
                tag = f"{name}{{{base}}}" if base else name
                if fam["type"] == "histogram":
                    writer.add_text(tag, json.dumps(
                        {k: s[k] for k in ("sum", "count", "buckets",
                                           "p50", "p99")}), step)
                else:
                    writer.add_scalar(tag, s["value"], step)
        writer.flush()

    def reset(self):
        """Drop every family and collector (test isolation)."""
        with self._lock:
            self._families.clear()
            self._collectors.clear()


_default = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry every component reports through."""
    return _default
