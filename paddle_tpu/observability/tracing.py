"""Cross-component span tracing with trace-id propagation.

Extends `paddle_tpu.profiler.RecordEvent` host spans into SPANS that carry
a **trace id** across component boundaries: the router mints one per
request, it rides the payload / the Request object (like sampling knobs)
through replica -> engine -> scheduler -> decode step, and training steps
emit named phase spans — so ONE exported Chrome/Perfetto file shows a
request's (or step's) full path across threads and components.

Contract:

  * `start_tracing()` / `stop_tracing()` bound a collection window (the
    module-level `_ACTIVE` flag keeps the off-path to one attribute read —
    the <2% overhead gate in bench.py's observability arm measures with it
    ON);
  * `span(name, component=..., trace_id=..., **attrs)` context manager
    records a Chrome `X` (complete) event with `args = {trace_id,
    component, **attrs}`; `trace_id=None` inherits the thread's current
    trace context;
  * `trace_context(trace_id)` sets that thread-local context — a worker
    picking up request R wraps its work in `trace_context(R.trace_id)` and
    every span (including plain profiler `RecordEvent`s, which mirror in
    here when tracing is active) lands correlated;
  * `export_chrome(path, device_trace_dir=...)` writes one
    ``{"traceEvents": [...]}`` JSON, merging any Chrome-format device
    traces `jax.profiler` produced under `device_trace_dir`
    (``**/*.trace.json[.gz]`` — TensorBoard's plugins/profile layout), so
    host spans and XLA device activity share one timeline.

Everything here is dependency-free host code — importable from the
scheduler/router hot paths without pulling jax.
"""
from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import threading
import time
import uuid

__all__ = ["start_tracing", "stop_tracing", "tracing_active", "span",
           "trace_context", "current_trace_id", "new_trace_id",
           "record_span", "export_chrome", "events_snapshot"]

_ACTIVE = False
_lock = threading.Lock()
_events: list[dict] = []
_MAX_EVENTS = 1_000_000  # hard cap: tracing must never OOM the host
_tls = threading.local()
# os.getpid() is a SYSCALL per call (tens of µs under gVisor-class
# sandboxes) — cache it; a fork gets a fresh module state anyway under
# the spawn start-method every paddle_tpu multiproc path uses
_PID = os.getpid()


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def tracing_active() -> bool:
    return _ACTIVE


def start_tracing():
    """Begin a collection window (clears previously collected spans)."""
    global _ACTIVE
    with _lock:
        _events.clear()
    _ACTIVE = True


def stop_tracing() -> list:
    """End the window; returns the collected Chrome events."""
    global _ACTIVE
    _ACTIVE = False
    with _lock:
        return list(_events)


def events_snapshot() -> list:
    with _lock:
        return list(_events)


def reset():
    """Stop collection AND drop collected events (test isolation —
    stop_tracing alone keeps them for export)."""
    global _ACTIVE
    _ACTIVE = False
    with _lock:
        _events.clear()


def current_trace_id() -> str | None:
    return getattr(_tls, "trace_id", None)


@contextlib.contextmanager
def trace_context(trace_id: str | None):
    """Bind `trace_id` as this thread's current trace — spans (and
    mirrored RecordEvents) inside inherit it. None is a no-op bind."""
    prev = getattr(_tls, "trace_id", None)
    _tls.trace_id = trace_id if trace_id is not None else prev
    try:
        yield
    finally:
        _tls.trace_id = prev


def record_span(name: str, begin_ns: int, dur_ns: int,
                args: dict | None = None):
    """Low-level sink (profiler.RecordEvent mirrors through this): one
    Chrome complete event; the thread's current trace id is attached when
    the caller didn't set one."""
    if not _ACTIVE:
        return
    a = dict(args) if args else {}
    if "trace_id" not in a:
        tid = getattr(_tls, "trace_id", None)
        if tid is not None:
            a["trace_id"] = tid
    ev = {"name": name, "ph": "X", "ts": begin_ns / 1e3,
          "dur": dur_ns / 1e3, "pid": _PID,
          "tid": threading.get_ident(), "args": a}
    with _lock:
        if len(_events) < _MAX_EVENTS:
            _events.append(ev)


class span:
    """Context manager recording one span when tracing is active. With
    `bind=True` (default) the span also binds its trace id as the thread
    context for its duration, so nested spans (and plain RecordEvents)
    correlate. Pass `bind=False` when the span wraps a GENERATOR's
    lifetime (e.g. the router's per-request stream): a suspended
    generator's `with` stays entered across unrelated work on the
    consumer thread, and interleaved generators would restore the
    thread-local non-LIFO — the span still CARRIES the id, it just must
    not own the thread context."""

    __slots__ = ("name", "component", "trace_id", "attrs", "bind",
                 "_begin", "_prev")

    def __init__(self, name: str, component: str = "",
                 trace_id: str | None = None, bind: bool = True, **attrs):
        self.name = name
        self.component = component
        self.trace_id = trace_id
        self.bind = bind
        self.attrs = attrs
        self._begin = None
        self._prev = None

    def __enter__(self):
        if _ACTIVE:
            self._begin = time.perf_counter_ns()
            if self.trace_id is not None and self.bind:
                self._prev = getattr(_tls, "trace_id", None)
                _tls.trace_id = self.trace_id
        return self

    def __exit__(self, *a):
        if self._begin is not None:
            args = dict(self.attrs)
            if self.component:
                args["component"] = self.component
            if self.trace_id is not None:
                args["trace_id"] = self.trace_id
                if self.bind:
                    _tls.trace_id = self._prev
            record_span(self.name, self._begin,
                        time.perf_counter_ns() - self._begin, args)
            self._begin = None
        return False


def _device_trace_events(device_trace_dir: str) -> list:
    """Chrome events from a jax.profiler trace directory, when the backend
    exported Chrome-format traces (TensorBoard layout:
    ``<dir>/plugins/profile/<run>/*.trace.json[.gz]``). xplane-only dumps
    merge nothing — the host timeline still stands alone."""
    out = []
    for pat in ("**/*.trace.json", "**/*.trace.json.gz"):
        for p in glob.glob(os.path.join(device_trace_dir, pat),
                           recursive=True):
            try:
                if p.endswith(".gz"):
                    with gzip.open(p, "rt") as f:
                        data = json.load(f)
                else:
                    with open(p) as f:
                        data = json.load(f)
            except (OSError, ValueError) as e:
                out.append({"name": f"device-trace-unreadable: {p}: {e}",
                            "ph": "i", "ts": 0, "pid": 0, "tid": 0,
                            "s": "g"})
                continue
            evs = (data.get("traceEvents", data)
                   if isinstance(data, dict) else data)
            if isinstance(evs, list):
                out.extend(e for e in evs if isinstance(e, dict))
    return out


def export_chrome(path: str, device_trace_dir: str | None = None,
                  extra_events: list | None = None) -> dict:
    """Write the collected spans (plus optional merged device trace and
    caller-supplied events) as ONE Chrome trace file. Returns summary
    counts {host_events, device_events, path}."""
    with _lock:
        events = list(_events)
    n_host = len(events)
    if extra_events:
        events.extend(extra_events)
    n_dev = 0
    if device_trace_dir is not None and os.path.isdir(device_trace_dir):
        dev = _device_trace_events(device_trace_dir)
        n_dev = len(dev)
        events.extend(dev)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ms"}, f)
    return {"host_events": n_host, "device_events": n_dev, "path": path}
