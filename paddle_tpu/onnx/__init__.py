"""paddle.onnx (reference: python/paddle/onnx/export.py via paddle2onnx).

The image ships no `onnx` package, so export is gated: with onnx installed
this raises NotImplementedError pointing at the StableHLO artifact
(`jit.save`), which is the TPU-native deployment format; without onnx the
ImportError is surfaced directly.
"""
__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "paddle.onnx.export requires the `onnx` package, which is not "
            "installed in this environment; use paddle.jit.save for the "
            "portable StableHLO deployment artifact instead") from e
    raise NotImplementedError(
        "ONNX conversion from StableHLO is not implemented; deploy with "
        "paddle.jit.save / paddle.inference")
