"""Op library + Tensor method installation.

Mirrors the reference flow where ops.yaml codegen attaches per-op methods to the
eager tensor (python_c_gen.py -> core.eager.ops -> monkey-patched tensor methods
in python/paddle/tensor/__init__.py). Here the op library is plain Python over
jax; `install_tensor_methods()` attaches the method surface once at import."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor, apply_op

from paddle_tpu.ops import (  # noqa: F401
    comparison,
    creation,
    linalg,
    manipulation,
    math,
    reduction,
)
from paddle_tpu.ops.comparison import *  # noqa: F401,F403
from paddle_tpu.ops.creation import *  # noqa: F401,F403
from paddle_tpu.ops.extras import *  # noqa: F401,F403
from paddle_tpu.ops.linalg import *  # noqa: F401,F403
from paddle_tpu.ops.manipulation import *  # noqa: F401,F403
from paddle_tpu.ops.math import *  # noqa: F401,F403
from paddle_tpu.ops.reduction import *  # noqa: F401,F403
from paddle_tpu.ops.random_state import seed  # noqa: F401


def _coerce_index(idx):
    """Convert Tensors inside an index expression to raw arrays (constants)."""
    if isinstance(idx, Tensor):
        return np.asarray(idx._value) if idx._value.dtype == np.bool_ else idx._value
    if isinstance(idx, tuple):
        return tuple(_coerce_index(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(np.asarray(idx))
    return idx


def _getitem(self, idx):
    cidx = _coerce_index(idx)
    if isinstance(cidx, np.ndarray) and cidx.dtype == np.bool_:
        # boolean mask -> dynamic shape; host-side gather
        return Tensor(jnp.asarray(np.asarray(self._value)[cidx]), stop_gradient=True)
    return apply_op(lambda v: v[cidx], self, name="getitem")


def _setitem(self, idx, value):
    cidx = _coerce_index(idx)
    val = value._value if isinstance(value, Tensor) else value
    if not self.stop_gradient and self._grad_node is not None:
        # differentiable in-place update: record as an op rewriting this tensor
        out = apply_op(lambda v, u: v.at[cidx].set(jnp.asarray(u, v.dtype)),
                       self, value if isinstance(value, Tensor) else Tensor(jnp.asarray(val)),
                       name="setitem")
        self._set_value(out._value)
        self._grad_node = out._grad_node
        self._output_index = out._output_index
        return
    self._set_value(self._value.at[cidx].set(jnp.asarray(val, self._value.dtype)))


_BINARY = {
    "__add__": math.add,
    "__sub__": math.subtract,
    "__mul__": math.multiply,
    "__truediv__": math.divide,
    "__floordiv__": math.floor_divide,
    "__mod__": math.remainder,
    "__matmul__": linalg.matmul,
    "__pow__": math.pow,
    "__lt__": comparison.less_than,
    "__le__": comparison.less_equal,
    "__gt__": comparison.greater_than,
    "__ge__": comparison.greater_equal,
    "__and__": comparison.logical_and,
    "__or__": comparison.logical_or,
    "__xor__": comparison.logical_xor,
}

_RBINARY = {
    "__radd__": lambda x, y: math.add(y if isinstance(y, Tensor) else Tensor(jnp.asarray(y, x._value.dtype)), x),
    "__rsub__": lambda x, y: math.subtract(Tensor(jnp.asarray(y, x._value.dtype)), x),
    "__rmul__": lambda x, y: math.multiply(Tensor(jnp.asarray(y, x._value.dtype)), x),
    "__rtruediv__": lambda x, y: math.divide(Tensor(jnp.asarray(y, x._value.dtype)), x),
    "__rpow__": lambda x, y: math.pow(Tensor(jnp.asarray(y, x._value.dtype)), x),
    "__rmatmul__": lambda x, y: linalg.matmul(Tensor(jnp.asarray(y)), x),
}


_METHODS = [
    # math
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder", "mod",
    "pow", "exp", "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "abs",
    "sign", "square", "reciprocal", "floor", "ceil", "round", "trunc", "sin",
    "cos", "tan", "tanh", "erf", "maximum", "minimum", "clip", "scale",
    "isnan", "isinf", "isfinite", "lerp", "expm1", "sinh", "cosh", "asin",
    "acos", "atan",
    # reduction
    "sum", "mean", "max", "min", "prod", "argmax", "argmin", "all", "any",
    "logsumexp", "std", "var", "cumsum", "cumprod", "median",
    # manipulation
    "reshape", "transpose", "squeeze", "unsqueeze", "flatten", "cast",
    "gather", "gather_nd", "scatter", "index_select", "tile", "expand",
    "expand_as", "broadcast_to", "flip", "roll", "split", "chunk", "topk",
    "sort", "argsort", "unbind", "numel", "take_along_axis", "put_along_axis",
    "masked_fill", "repeat_interleave", "flatten", "pad", "where",
    "tensor_split", "view", "view_as", "moveaxis",
    # linalg
    "matmul", "mm", "bmm", "dot", "norm", "dist", "inv", "cholesky", "det",
    "outer", "kron", "mv",
    # comparison
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_not", "allclose",
    "isclose", "equal_all", "bitwise_and", "bitwise_or", "bitwise_xor",
    "bitwise_not",
    # creation-ish
    "tril", "triu",
    # tail (ops/extras.py)
    "digamma", "lgamma", "i0", "i0e", "i1", "i1e", "polygamma",
    "logcumsumexp", "copysign", "heaviside", "nextafter", "ldexp",
    "nanmedian", "renorm", "trapezoid", "vander", "trace", "diagonal",
    "diag_embed", "fill_diagonal", "index_add", "index_put", "index_fill",
    "multiplex", "addmm", "as_strided", "unique_consecutive", "bucketize",
    "combinations", "bernoulli", "multinomial",
    "bitwise_left_shift", "bitwise_right_shift",
    # linalg tail
    "cholesky_solve", "matrix_exp", "corrcoef", "cov", "lu", "lu_unpack",
    # second tail batch
    "masked_scatter", "take", "frexp", "cdist", "diff", "signbit", "sinc",
    "isneginf", "isposinf", "isreal", "quantile", "nanquantile",
    "cartesian_prod", "unflatten", "gcd", "lcm", "isin", "nanargmax",
    "nanargmin", "select_scatter", "slice_scatter",
]

_installed = False


def install_tensor_methods():
    global _installed
    if _installed:
        return
    import paddle_tpu.ops as _ops_mod

    for name, fn in _BINARY.items():
        setattr(Tensor, name, (lambda f: lambda self, other: f(self, other))(fn))
    for name, fn in _RBINARY.items():
        setattr(Tensor, name, (lambda f: lambda self, other: f(self, other))(fn))
    Tensor.__neg__ = lambda self: math.neg(self)
    Tensor.__abs__ = lambda self: math.abs(self)
    Tensor.__invert__ = lambda self: comparison.logical_not(self)
    Tensor.__eq__ = lambda self, other: comparison.equal(self, other)
    Tensor.__ne__ = lambda self, other: comparison.not_equal(self, other)
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem

    for name in _METHODS:
        fn = getattr(_ops_mod, name, None)
        if fn is None:
            continue
        if not hasattr(Tensor, name):
            setattr(Tensor, name, (lambda f: lambda self, *a, **k: f(self, *a, **k))(fn))

    def astype(self, dtype):
        return manipulation.cast(self, dtype)

    Tensor.astype = astype
    Tensor.item = Tensor.item  # keep
    _installed = True


install_tensor_methods()
