"""Comparison / logical / bitwise ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor, apply_op

__all__ = [
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "equal_all", "allclose", "isclose", "logical_and",
    "logical_or", "logical_xor", "logical_not", "bitwise_and", "bitwise_or",
    "bitwise_xor", "bitwise_not", "is_empty",
]


def _t(x, like=None):
    if isinstance(x, Tensor):
        return x
    if like is not None:
        return Tensor(jnp.asarray(x, like._value.dtype))
    return Tensor(jnp.asarray(x))


def _cmp(fn, name):
    def op(x, y):
        x = _t(x)
        y = _t(y, like=x)
        return apply_op(fn, x, y, name=name)

    op.__name__ = name
    return op


equal = _cmp(jnp.equal, "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")


def logical_not(x):
    return apply_op(jnp.logical_not, _t(x), name="logical_not")


def bitwise_not(x):
    return apply_op(jnp.bitwise_not, _t(x), name="bitwise_not")


def equal_all(x, y):
    return apply_op(lambda a, b: jnp.array_equal(a, b), _t(x), _t(y), name="equal_all")


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return apply_op(
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        _t(x), _t(y), name="allclose",
    )


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return apply_op(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        _t(x), _t(y), name="isclose",
    )


def is_empty(x):
    return Tensor(jnp.asarray(_t(x).size == 0))
