"""Tensor creation ops (reference: python/paddle/tensor/creation.py; kernels in
paddle/phi/kernels/*full*, *arange* etc.). All lower directly to jax.numpy."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dtype import get_default_dtype, to_jax_dtype
from paddle_tpu.core.tensor import Tensor, to_tensor
from paddle_tpu.ops.random_state import default_generator

__all__ = [
    "zeros", "ones", "full", "empty", "zeros_like", "ones_like", "full_like",
    "empty_like", "arange", "linspace", "eye", "rand", "randn", "randint",
    "uniform", "normal", "randperm", "tril", "triu", "diag", "diagflat",
    "meshgrid", "to_tensor", "assign", "clone_detached", "tril_indices",
    "triu_indices", "one_hot",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def _dt(dtype, default=None):
    d = to_jax_dtype(dtype)
    if d is None:
        d = default or get_default_dtype().np_dtype
    return d


def zeros(shape, dtype=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None and isinstance(fill_value, bool):
        dtype = "bool"
    elif dtype is None and isinstance(fill_value, int):
        dtype = "int64"
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None):
    return Tensor(jnp.zeros(x._value.shape, _dt(dtype, x._value.dtype)))


def ones_like(x, dtype=None):
    return Tensor(jnp.ones(x._value.shape, _dt(dtype, x._value.dtype)))


def full_like(x, fill_value, dtype=None):
    return Tensor(jnp.full(x._value.shape, fill_value, _dt(dtype, x._value.dtype)))


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, float):
            dtype = dtype or get_default_dtype()
    d = to_jax_dtype(dtype) if dtype is not None else np.int64
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None):
    return Tensor(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def rand(shape, dtype=None):
    key = default_generator.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0):
    key = jax.random.key(seed) if seed else default_generator.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype), minval=min, maxval=max))


def randn(shape, dtype=None):
    key = default_generator.next_key()
    return Tensor(jax.random.normal(key, _shape(shape), _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None):
    key = default_generator.next_key()
    return Tensor(mean + std * jax.random.normal(key, _shape(shape)))


def randint(low=0, high=None, shape=(1,), dtype=None):
    if high is None:
        low, high = 0, low
    key = default_generator.next_key()
    d = to_jax_dtype(dtype) or np.int64
    return Tensor(jax.random.randint(key, _shape(shape), low, high, dtype=d))


def randperm(n, dtype=None):
    key = default_generator.next_key()
    d = to_jax_dtype(dtype) or np.int64
    return Tensor(jax.random.permutation(key, n).astype(d))


def tril(x, diagonal=0):
    from paddle_tpu.core.tensor import apply_op

    return apply_op(lambda v: jnp.tril(v, diagonal), x, name="tril")


def triu(x, diagonal=0):
    from paddle_tpu.core.tensor import apply_op

    return apply_op(lambda v: jnp.triu(v, diagonal), x, name="triu")


def diag(x, offset=0):
    from paddle_tpu.core.tensor import apply_op

    return apply_op(lambda v: jnp.diag(v, offset), x, name="diag")


def diagflat(x, offset=0):
    from paddle_tpu.core.tensor import apply_op

    return apply_op(lambda v: jnp.diagflat(v, offset), x, name="diagflat")


def meshgrid(*args):
    arrs = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    return [Tensor(m) for m in jnp.meshgrid(*arrs, indexing="ij")]


def assign(x, output=None):
    val = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    if output is not None:
        output._set_value(val)
        return output
    return Tensor(val)


def clone_detached(x):
    return Tensor(x._value)


def tril_indices(row, col, offset=0):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]).astype(np.int64)))


def triu_indices(row, col, offset=0):
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]).astype(np.int64)))


def one_hot(x, num_classes):
    from paddle_tpu.core.tensor import apply_op

    return apply_op(
        lambda v: jax.nn.one_hot(v, num_classes, dtype=get_default_dtype().np_dtype),
        x,
        name="one_hot",
    )
