"""Op-surface tail: special functions, index mutations, samplers, misc.

Reference parity targets (all in /root/reference/paddle/phi/ops/yaml/ops.yaml
with kernels under paddle/phi/kernels/): digamma, lgamma, polygamma, i0/i0e/
i1/i1e, gammaincc, logcumsumexp, copysign, heaviside, nextafter, ldexp,
nanmedian, renorm, logspace, trapezoid, vander, trace, diagonal, diag_embed,
fill_diagonal, index_add/index_put/index_fill, multiplex, addmm, complex,
broadcast_tensors, as_strided, unique_consecutive, bucketize, histogramdd,
combinations, bernoulli, poisson, multinomial, standard_gamma,
bitwise_left_shift, bitwise_right_shift.

TPU notes: everything static-shaped lowers through apply_op -> XLA; the
dynamic-output ops (unique_consecutive, combinations' host index build) use
the same host-numpy pattern as `unique` (dynamic shapes cannot live in XLA
programs). Samplers draw from the lazy default_generator.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.scipy.special as jsp
import numpy as np

from paddle_tpu.core.dtype import to_jax_dtype
from paddle_tpu.core.tensor import Tensor, apply_op
from paddle_tpu.ops.random_state import default_generator

__all__ = [
    "digamma", "lgamma", "gammaln", "gammainc", "gammaincc", "polygamma",
    "i0", "i0e", "i1", "i1e", "logcumsumexp", "copysign", "heaviside",
    "nextafter", "ldexp", "nanmedian", "renorm", "logspace", "trapezoid",
    "vander", "trace", "diagonal", "diag_embed", "fill_diagonal", "index_add",
    "index_put", "index_fill", "multiplex", "addmm", "complex",
    "broadcast_tensors", "as_strided", "unique_consecutive", "bucketize",
    "histogramdd", "combinations", "bernoulli", "poisson", "multinomial",
    "standard_gamma", "bitwise_left_shift", "bitwise_right_shift",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _unary(fn, name):
    def op(x, name_arg=None):
        return apply_op(fn, _t(x), name=name)

    op.__name__ = name
    return op


# -- special functions -------------------------------------------------------
digamma = _unary(jsp.digamma, "digamma")
lgamma = _unary(jsp.gammaln, "lgamma")
gammaln = _unary(jsp.gammaln, "gammaln")
i0 = _unary(jsp.i0, "i0")
i0e = _unary(jsp.i0e, "i0e")
i1 = _unary(jsp.i1, "i1")
i1e = _unary(jsp.i1e, "i1e")


def gammainc(x, y):
    return apply_op(jsp.gammainc, _t(x), _t(y), name="gammainc")


def gammaincc(x, y):
    return apply_op(jsp.gammaincc, _t(x), _t(y), name="gammaincc")


def polygamma(x, n):
    return apply_op(lambda v: jsp.polygamma(int(n), v), _t(x), name="polygamma")


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def f(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else axis
        return jax.lax.associative_scan(jnp.logaddexp, vv, axis=ax)

    return apply_op(f, _t(x), name="logcumsumexp")


# -- elementwise binary tail -------------------------------------------------
def copysign(x, y):
    return apply_op(jnp.copysign, _t(x), _t(y), name="copysign")


def heaviside(x, y):
    return apply_op(jnp.heaviside, _t(x), _t(y), name="heaviside")


def nextafter(x, y):
    return apply_op(jnp.nextafter, _t(x), _t(y), name="nextafter")


def ldexp(x, y):
    return apply_op(lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)), _t(x), _t(y),
                    name="ldexp")


def bitwise_left_shift(x, y, is_arithmetic=True):
    return apply_op(jnp.left_shift, _t(x), _t(y), name="bitwise_left_shift")


def bitwise_right_shift(x, y, is_arithmetic=True):
    fn = jnp.right_shift if is_arithmetic else (
        lambda a, b: jax.lax.shift_right_logical(a, b.astype(a.dtype)))
    return apply_op(fn, _t(x), _t(y), name="bitwise_right_shift")


# -- reductions / stats ------------------------------------------------------
def nanmedian(x, axis=None, keepdim=False, mode="avg"):
    def f(v):
        if mode == "min":  # lower of the two middle elements
            def med1d(a):
                a = jnp.sort(a)
                n = (~jnp.isnan(a)).sum()
                return a[jnp.maximum((n - 1) // 2, 0)]

            if axis is None:
                return med1d(v.reshape(-1))
            mv = jnp.apply_along_axis(med1d, axis, v)
            return jnp.expand_dims(mv, axis) if keepdim else mv
        return jnp.nanmedian(v, axis=axis, keepdims=keepdim)

    return apply_op(f, _t(x), name="nanmedian")


def renorm(x, p, axis, max_norm):
    def f(v):
        moved = jnp.moveaxis(v, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        out = flat * factor[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)

    return apply_op(f, _t(x), name="renorm")


def trapezoid(y, x=None, dx=None, axis=-1, mode="sum"):
    if x is not None:
        return apply_op(lambda yy, xx: jnp.trapezoid(yy, xx, axis=axis),
                        _t(y), _t(x), name="trapezoid")
    return apply_op(lambda yy: jnp.trapezoid(yy, dx=dx if dx is not None else 1.0,
                                             axis=axis), _t(y), name="trapezoid")


def histogramdd(x, bins=10, ranges=None, density=False, weights=None):
    v = np.asarray(_t(x)._value)
    w = None if weights is None else np.asarray(_t(weights)._value)
    hist, edges = np.histogramdd(v, bins=bins, range=ranges, density=density,
                                 weights=w)
    return Tensor(jnp.asarray(hist)), [Tensor(jnp.asarray(e)) for e in edges]


# -- creation / views --------------------------------------------------------
def logspace(start, stop, num, base=10.0, dtype=None):
    return Tensor(jnp.logspace(float(start), float(stop), int(num),
                               base=float(base), dtype=to_jax_dtype(dtype)))


def vander(x, n=None, increasing=False):
    return apply_op(lambda v: jnp.vander(v, N=n, increasing=increasing), _t(x),
                    name="vander")


def trace(x, offset=0, axis1=0, axis2=1):
    return apply_op(lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2),
                    _t(x), name="trace")


def diagonal(x, offset=0, axis1=0, axis2=1):
    return apply_op(
        lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2),
        _t(x), name="diagonal")


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    def f(v):
        k = v.shape[-1]
        m = k + abs(offset)
        r = jnp.arange(k) + max(-offset, 0)
        c = jnp.arange(k) + max(offset, 0)
        out = jnp.zeros(v.shape[:-1] + (m, m), v.dtype).at[..., r, c].set(v)
        # place the two new axes at dim1/dim2
        nd = out.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        if (d1, d2) != (nd - 2, nd - 1):
            out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
        return out

    return apply_op(f, _t(x), name="diag_embed")


def fill_diagonal(x, value, offset=0, wrap=False):
    def f(v):
        k = min(v.shape[-2] - max(-offset, 0), v.shape[-1] - max(offset, 0))
        r = jnp.arange(k) + max(-offset, 0)
        c = jnp.arange(k) + max(offset, 0)
        return v.at[..., r, c].set(value)

    return apply_op(f, _t(x), name="fill_diagonal")


def as_strided(x, shape, stride, offset=0):
    def f(v):
        flat = v.reshape(-1)
        idx = jnp.asarray(offset)
        for s, st in zip(shape, stride):
            idx = idx[..., None] + jnp.arange(s) * st
        return flat[idx.reshape(-1)].reshape(tuple(shape))

    return apply_op(f, _t(x), name="as_strided")


def broadcast_tensors(inputs):
    ts = [_t(t) for t in inputs]
    outs = apply_op(lambda *vs: tuple(jnp.broadcast_arrays(*vs)), *ts,
                    name="broadcast_tensors")
    return list(outs)


def complex(real, imag):
    return apply_op(lambda r, i: jax.lax.complex(r, i), _t(real), _t(imag),
                    name="complex")


# -- index mutations ---------------------------------------------------------
def index_add(x, index, axis, value):
    def f(v, idx, val):
        moved = jnp.moveaxis(v, axis, 0)
        vmoved = jnp.moveaxis(val, axis, 0)
        out = moved.at[idx].add(vmoved)
        return jnp.moveaxis(out, 0, axis)

    return apply_op(f, _t(x), _t(index), _t(value), name="index_add")


def index_fill(x, index, axis, fill_value):
    def f(v, idx):
        moved = jnp.moveaxis(v, axis, 0)
        out = moved.at[idx].set(fill_value)
        return jnp.moveaxis(out, 0, axis)

    return apply_op(f, _t(x), _t(index), name="index_fill")


def index_put(x, indices, value, accumulate=False):
    idx_ts = [_t(i) for i in indices]

    def f(v, val, *idx):
        if accumulate:
            return v.at[tuple(idx)].add(val)
        return v.at[tuple(idx)].set(val)

    return apply_op(f, _t(x), _t(value), *idx_ts, name="index_put")


def multiplex(inputs, index):
    ts = [_t(t) for t in inputs]

    def f(idx, *vs):
        stacked = jnp.stack(vs)  # [K, N, ...]
        rows = idx.reshape(-1).astype(jnp.int32)
        return stacked[rows, jnp.arange(rows.shape[0])]

    return apply_op(f, _t(index), *ts, name="multiplex")


def addmm(input, x, y, beta=1.0, alpha=1.0):
    return apply_op(lambda i, a, b: beta * i + alpha * (a @ b),
                    _t(input), _t(x), _t(y), name="addmm")


# -- dynamic-shape (host) ----------------------------------------------------
def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64"):
    v = np.asarray(_t(x)._value)
    if axis is None:
        flat = v.reshape(-1)
        if flat.size == 0:
            keep = np.zeros(0, bool)
        else:
            keep = np.concatenate([[True], flat[1:] != flat[:-1]])
        out = flat[keep]
        outs = [Tensor(jnp.asarray(out))]
        if return_inverse:
            inv = np.cumsum(keep) - 1
            outs.append(Tensor(jnp.asarray(inv.astype(np.int64))))
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.append(idx, flat.size))
            outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    else:
        moved = np.moveaxis(v, axis, 0)
        if moved.shape[0] == 0:
            keep = np.zeros(0, bool)
        else:
            diff = (moved[1:] != moved[:-1]).reshape(moved.shape[0] - 1, -1).any(1)
            keep = np.concatenate([[True], diff])
        out = np.moveaxis(moved[keep], 0, axis)
        outs = [Tensor(jnp.asarray(out))]
        if return_inverse:
            outs.append(Tensor(jnp.asarray((np.cumsum(keep) - 1).astype(np.int64))))
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.append(idx, moved.shape[0]))
            outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def bucketize(x, sorted_sequence, out_int32=False, right=False):
    def f(v, seq):
        side = "right" if right else "left"
        out = jnp.searchsorted(seq, v, side=side)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)

    return apply_op(f, _t(x), _t(sorted_sequence), name="bucketize")


def combinations(x, r=2, with_replacement=False):
    import itertools

    n = int(_t(x)._value.shape[0])
    gen = itertools.combinations_with_replacement if with_replacement else \
        itertools.combinations
    idx = np.array(list(gen(range(n), r)), np.int32).reshape(-1, r)

    return apply_op(lambda v: v[jnp.asarray(idx)], _t(x), name="combinations")


# -- samplers ----------------------------------------------------------------
def bernoulli(x, name=None):
    key = default_generator.next_key()
    return apply_op(lambda p, k: jax.random.bernoulli(k, p).astype(p.dtype),
                    _t(x), key, name="bernoulli", rng_args=(1,))


def poisson(x, name=None):
    key = default_generator.next_key()
    return apply_op(lambda lam, k: jax.random.poisson(k, lam).astype(lam.dtype),
                    _t(x), key, name="poisson", rng_args=(1,))


def standard_gamma(x, name=None):
    key = default_generator.next_key()
    return apply_op(lambda a, k: jax.random.gamma(k, a).astype(a.dtype),
                    _t(x), key, name="standard_gamma", rng_args=(1,))


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = default_generator.next_key()

    def f(p, k):
        logits = jnp.log(jnp.maximum(p, 1e-30))
        if replacement:
            out = jax.random.categorical(k, logits, axis=-1,
                                         shape=(num_samples,) + p.shape[:-1])
            return jnp.moveaxis(out, 0, -1).astype(jnp.int64)
        if p.ndim == 1:
            return jax.random.choice(k, p.shape[0], (num_samples,),
                                     replace=False, p=p / p.sum()).astype(jnp.int64)
        keys = jax.random.split(k, p.shape[0])
        return jax.vmap(
            lambda kk, pp: jax.random.choice(kk, p.shape[-1], (num_samples,),
                                             replace=False, p=pp / pp.sum())
        )(keys, p).astype(jnp.int64)

    return apply_op(f, _t(x), key, name="multinomial", rng_args=(1,))


# -- second tail batch: stacking/splitting, distance, nan-aware, misc --------
def masked_scatter(x, mask, value):
    def f(v, m, val):
        flat_val = val.reshape(-1)
        mf = m.reshape(-1).astype(bool)
        # k-th True in mask takes value[k] (reference masked_scatter contract)
        pos = jnp.cumsum(mf) - 1
        picked = flat_val[jnp.clip(pos, 0, flat_val.shape[0] - 1)]
        return jnp.where(mf, picked, v.reshape(-1)).reshape(v.shape)

    return apply_op(f, _t(x), _t(mask), _t(value), name="masked_scatter")


def take(x, index, mode="raise"):
    def f(v, idx):
        flat = v.reshape(-1)
        n = flat.shape[0]
        if mode == "wrap":
            idx = jnp.mod(idx, n)
        else:  # raise/clip: XLA cannot raise; both clamp like the clip mode
            idx = jnp.clip(idx, -n, n - 1)
        return flat[jnp.where(idx < 0, idx + n, idx)]

    return apply_op(f, _t(x), _t(index), name="take")


def frexp(x):
    return apply_op(lambda v: tuple(jnp.frexp(v)), _t(x), name="frexp")


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary"):
    def f(a, b):
        d = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
        if p == float("inf"):
            return d.max(-1)
        return (d ** p).sum(-1) ** (1.0 / p)

    return apply_op(f, _t(x), _t(y), name="cdist")


def pdist(x, p=2.0):
    def f(a):
        n = a.shape[0]
        d = jnp.abs(a[:, None, :] - a[None, :, :])
        dist = d.max(-1) if p == float("inf") else (d ** p).sum(-1) ** (1.0 / p)
        iu = jnp.triu_indices(n, k=1)
        return dist[iu]

    return apply_op(f, _t(x), name="pdist")


def diff(x, n=1, axis=-1, prepend=None, append=None):
    pre = None if prepend is None else _t(prepend)
    app = None if append is None else _t(append)
    args = [_t(x)] + [a for a in (pre, app) if a is not None]

    def f(v, *rest):
        i = 0
        kw = {}
        if pre is not None:
            kw["prepend"] = rest[i]
            i += 1
        if app is not None:
            kw["append"] = rest[i]
        return jnp.diff(v, n=n, axis=axis, **kw)

    return apply_op(f, *args, name="diff")


def signbit(x):
    return apply_op(jnp.signbit, _t(x), name="signbit")


def sinc(x):
    return apply_op(jnp.sinc, _t(x), name="sinc")


def isneginf(x):
    return apply_op(jnp.isneginf, _t(x), name="isneginf")


def isposinf(x):
    return apply_op(jnp.isposinf, _t(x), name="isposinf")


def isreal(x):
    return apply_op(jnp.isreal, _t(x), name="isreal")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    def f(v):
        return jnp.quantile(v, jnp.asarray(q), axis=axis, keepdims=keepdim,
                            method=interpolation)

    return apply_op(f, _t(x), name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    def f(v):
        return jnp.nanquantile(v, jnp.asarray(q), axis=axis, keepdims=keepdim,
                               method=interpolation)

    return apply_op(f, _t(x), name="nanquantile")


def msort(x):
    return apply_op(lambda v: jnp.sort(v, axis=0), _t(x), name="msort")


def cartesian_prod(xs):
    ts = [_t(t) for t in xs]

    def f(*vs):
        grids = jnp.meshgrid(*vs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)

    return apply_op(f, *ts, name="cartesian_prod")


def block_diag(inputs):
    ts = [_t(t) for t in inputs]

    def f(*vs):
        vs = [v.reshape(1, 1) if v.ndim == 0 else
              (v.reshape(1, -1) if v.ndim == 1 else v) for v in vs]
        rows = sum(v.shape[0] for v in vs)
        cols = sum(v.shape[1] for v in vs)
        out = jnp.zeros((rows, cols), vs[0].dtype)
        r = c = 0
        for v in vs:
            out = jax.lax.dynamic_update_slice(out, v.astype(out.dtype), (r, c))
            r += v.shape[0]
            c += v.shape[1]
        return out

    return apply_op(f, *ts, name="block_diag")


def unflatten(x, axis, shape):
    def f(v):
        ax = axis % v.ndim
        new = v.shape[:ax] + tuple(shape) + v.shape[ax + 1:]
        return v.reshape(new)

    return apply_op(f, _t(x), name="unflatten")


def positive(x):
    return apply_op(lambda v: +v, _t(x), name="positive")


def negative(x):
    return apply_op(lambda v: -v, _t(x), name="negative")


def gcd(x, y):
    return apply_op(jnp.gcd, _t(x), _t(y), name="gcd")


def lcm(x, y):
    return apply_op(jnp.lcm, _t(x), _t(y), name="lcm")


def isin(x, test_x, assume_unique=False, invert=False):
    return apply_op(lambda v, s: jnp.isin(v, s, invert=invert), _t(x),
                    _t(test_x), name="isin")


def nanargmax(x, axis=None, keepdim=False):
    def f(v):
        out = jnp.nanargmax(v, axis=axis)
        return jnp.expand_dims(out, axis) if (keepdim and axis is not None) else out

    return apply_op(f, _t(x), name="nanargmax")


def nanargmin(x, axis=None, keepdim=False):
    def f(v):
        out = jnp.nanargmin(v, axis=axis)
        return jnp.expand_dims(out, axis) if (keepdim and axis is not None) else out

    return apply_op(f, _t(x), name="nanargmin")


def _stack_family(fn, name):
    def op(inputs):
        ts = [_t(t) for t in inputs]
        return apply_op(lambda *vs: fn(vs), *ts, name=name)

    op.__name__ = name
    return op


column_stack = _stack_family(jnp.column_stack, "column_stack")
row_stack = _stack_family(jnp.vstack, "row_stack")
hstack = _stack_family(jnp.hstack, "hstack")
vstack = _stack_family(jnp.vstack, "vstack")
dstack = _stack_family(jnp.dstack, "dstack")


def _split_family(axis_name, name):
    def op(x, num_or_indices, name_arg=None):
        def f(v):
            return tuple(jnp.array_split(v, num_or_indices, axis=axis_name)
                         if isinstance(num_or_indices, int)
                         else jnp.split(v, num_or_indices, axis=axis_name))

        return list(apply_op(f, _t(x), name=name))

    op.__name__ = name
    return op


hsplit = _split_family(1, "hsplit")
vsplit = _split_family(0, "vsplit")
dsplit = _split_family(2, "dsplit")


def select_scatter(x, values, axis, index):
    def f(v, val):
        idx = [slice(None)] * v.ndim
        idx[axis] = index
        return v.at[tuple(idx)].set(val)

    return apply_op(f, _t(x), _t(values), name="select_scatter")


def slice_scatter(x, value, axes, starts, ends, strides):
    def f(v, val):
        idx = [slice(None)] * v.ndim
        for ax, st, en, sr in zip(axes, starts, ends, strides):
            idx[ax] = slice(st, en, sr)
        return v.at[tuple(idx)].set(val)

    return apply_op(f, _t(x), _t(value), name="slice_scatter")


__all__ += [
    "masked_scatter", "take", "frexp", "cdist", "pdist", "diff", "signbit",
    "sinc", "isneginf", "isposinf", "isreal", "quantile", "nanquantile",
    "msort", "cartesian_prod", "block_diag", "unflatten", "positive",
    "negative", "gcd", "lcm", "isin", "nanargmax", "nanargmin",
    "column_stack", "row_stack", "hstack", "vstack", "dstack", "hsplit",
    "vsplit", "dsplit", "select_scatter", "slice_scatter",
]
