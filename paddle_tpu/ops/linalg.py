"""Linear algebra ops (reference: python/paddle/tensor/linalg.py — matmul at
linalg.py:177 dispatching to _C_ops.matmul). On TPU, matmul lowers straight to
the MXU via XLA dot_general; precision is controlled by FLAGS_tpu_matmul_precision."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.flags import flag
from paddle_tpu.core.tensor import Tensor, apply_op

__all__ = [
    "matmul", "mm", "bmm", "dot", "t", "outer", "inner", "cross", "norm",
    "dist", "cond", "einsum", "matrix_power", "multi_dot", "cholesky", "qr",
    "svd", "eig", "eigh", "eigvals", "eigvalsh", "inv", "pinv", "solve",
    "triangular_solve", "lstsq", "lu", "lu_unpack", "cholesky_solve",
    "matrix_exp", "householder_product", "cov", "corrcoef", "det", "slogdet",
    "matrix_rank", "histogram", "mv", "kron",
]


def _prec():
    p = flag("tpu_matmul_precision")
    return None if p == "default" else p


def _t_(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b, precision=_prec())

    return apply_op(f, _t_(x), _t_(y), name="matmul")


def mm(x, y):
    return matmul(x, y)


def bmm(x, y):
    return matmul(x, y)


def mv(x, vec):
    return apply_op(lambda a, b: jnp.matmul(a, b, precision=_prec()), _t_(x), _t_(vec), name="mv")


def dot(x, y):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=-1), _t_(x), _t_(y), name="dot")


def t(x):
    x = _t_(x)
    if x._value.ndim < 2:
        return x
    return apply_op(lambda v: jnp.swapaxes(v, -1, -2), x, name="t")


def outer(x, y):
    return apply_op(lambda a, b: jnp.outer(a, b), _t_(x), _t_(y), name="outer")


def inner(x, y):
    return apply_op(lambda a, b: jnp.inner(a, b), _t_(x), _t_(y), name="inner")


def cross(x, y, axis=9):
    ax = axis if axis != 9 else -1
    # paddle defaults to the first axis with dim 3
    if axis == 9:
        for i, s in enumerate(_t_(x)._value.shape):
            if s == 3:
                ax = i
                break
    return apply_op(lambda a, b: jnp.cross(a, b, axis=ax), _t_(x), _t_(y), name="cross")


def norm(x, p="fro", axis=None, keepdim=False):
    def f(v):
        if p == "fro" or (p == 2 and axis is None):
            return jnp.sqrt(jnp.sum(jnp.square(v), axis=axis, keepdims=keepdim))
        if p == np.inf or p == "inf":
            return jnp.max(jnp.abs(v), axis=axis, keepdims=keepdim)
        if p == -np.inf or p == "-inf":
            return jnp.min(jnp.abs(v), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=axis, keepdims=keepdim)
        return jnp.power(
            jnp.sum(jnp.power(jnp.abs(v), p), axis=axis, keepdims=keepdim), 1.0 / p
        )

    return apply_op(f, _t_(x), name="norm")


def dist(x, y, p=2):
    return norm(x - y, p=float(p) if p != np.inf else p)


def cond(x, p=None):
    return apply_op(lambda v: jnp.linalg.cond(v, p=p), _t_(x), name="cond")


def einsum(equation, *operands):
    ts = [_t_(o) for o in operands]
    return apply_op(
        lambda *vs: jnp.einsum(equation, *vs, precision=_prec()), *ts, name="einsum"
    )


def matrix_power(x, n):
    return apply_op(lambda v: jnp.linalg.matrix_power(v, n), _t_(x), name="matrix_power")


def multi_dot(xs):
    ts = [_t_(x) for x in xs]
    return apply_op(lambda *vs: jnp.linalg.multi_dot(vs, precision=_prec()), *ts, name="multi_dot")


def cholesky(x, upper=False):
    def f(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return apply_op(f, _t_(x), name="cholesky")


def qr(x, mode="reduced"):
    return apply_op(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), _t_(x), name="qr")


def svd(x, full_matrices=False):
    return apply_op(
        lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)), _t_(x), name="svd"
    )


def eig(x):
    v = np.asarray(_t_(x)._value)
    w, vec = np.linalg.eig(v)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(vec))


def eigh(x, UPLO="L"):
    return apply_op(lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), _t_(x), name="eigh")


def eigvals(x):
    v = np.asarray(_t_(x)._value)
    return Tensor(jnp.asarray(np.linalg.eigvals(v)))


def eigvalsh(x, UPLO="L"):
    return apply_op(lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), _t_(x), name="eigvalsh")


def inv(x):
    return apply_op(jnp.linalg.inv, _t_(x), name="inv")


def pinv(x, rcond=1e-15, hermitian=False):
    return apply_op(
        lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), _t_(x), name="pinv"
    )


def solve(x, y):
    return apply_op(jnp.linalg.solve, _t_(x), _t_(y), name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    import jax.scipy.linalg as jsl

    def f(a, b):
        return jsl.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        )

    return apply_op(f, _t_(x), _t_(y), name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None):
    def f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv

    return apply_op(f, _t_(x), _t_(y), name="lstsq")


def lu(x, pivot=True, get_infos=False):
    """Packed LU factorization (reference: tensor/linalg.py lu — returns
    LU-packed matrix + 1-indexed sequential-swap pivots [+ info])."""
    import jax.scipy.linalg as jsl

    def f(v):
        lu_, piv = jnp.vectorize(jsl.lu_factor, signature="(m,n)->(m,n),(k)")(v)
        return lu_, (piv + 1).astype(jnp.int32)

    lu_t, piv_t = apply_op(f, _t_(x), name="lu")
    if get_infos:
        return lu_t, piv_t, Tensor(jnp.zeros((), jnp.int32))
    return lu_t, piv_t


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True):
    """P, L, U from `lu` output (reference: tensor/linalg.py lu_unpack).
    Pivots are LAPACK sequential row swaps, 1-indexed."""

    def f(lu_, piv):
        def one(lu2, piv1):
            m, n = lu2.shape
            k = min(m, n)
            L = jnp.tril(lu2[:, :k], -1) + jnp.eye(m, k, dtype=lu2.dtype)
            U = jnp.triu(lu2[:k, :])

            def body(i, perm):
                j = piv1[i] - 1
                pi, pj = perm[i], perm[j]
                return perm.at[i].set(pj).at[j].set(pi)

            perm = jax.lax.fori_loop(0, piv1.shape[0], body, jnp.arange(m))
            # A[perm] = L @ U  =>  A = P @ L @ U with P[perm[i], i] = 1
            P = jnp.zeros((m, m), lu2.dtype).at[perm, jnp.arange(m)].set(1.0)
            return P, L, U

        return jnp.vectorize(one, signature="(m,n),(k)->(m,m),(m,k),(k,n)")(lu_, piv)

    return apply_op(f, _t_(x), _t_(y), name="lu_unpack")


def cholesky_solve(x, y, upper=False):
    """Solve A z = x given y = Cholesky factor of A (reference:
    tensor/linalg.py cholesky_solve)."""
    import jax.scipy.linalg as jsl

    def f(b, chol):
        def one(b2, c2):
            return jsl.cho_solve((c2, not upper), b2)

        return jnp.vectorize(one, signature="(m,k),(m,m)->(m,k)")(b, chol)

    return apply_op(f, _t_(x), _t_(y), name="cholesky_solve")


def matrix_exp(x):
    import jax.scipy.linalg as jsl

    def f(v):
        return jnp.vectorize(jsl.expm, signature="(m,m)->(m,m)")(v)

    return apply_op(f, _t_(x), name="matrix_exp")


def householder_product(x, tau):
    """Q from Householder reflectors (reference: tensor/linalg.py
    householder_product; lowers to LAPACK orgqr's XLA analog)."""
    from jax.lax.linalg import householder_product as hh

    return apply_op(lambda a, t_: hh(a, t_), _t_(x), _t_(tau),
                    name="householder_product")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = None if fweights is None else _t_(fweights)._value
    aw = None if aweights is None else _t_(aweights)._value

    def f(v):
        return jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0,
                       fweights=fw, aweights=aw)

    return apply_op(f, _t_(x), name="cov")


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda v: jnp.corrcoef(v, rowvar=rowvar), _t_(x),
                    name="corrcoef")


def det(x):
    return apply_op(jnp.linalg.det, _t_(x), name="det")


def slogdet(x):
    return apply_op(lambda v: tuple(jnp.linalg.slogdet(v)), _t_(x), name="slogdet")


def matrix_rank(x, tol=None, hermitian=False):
    return apply_op(lambda v: jnp.linalg.matrix_rank(v, rtol=tol), _t_(x), name="matrix_rank")


def histogram(x, bins=100, min=0, max=0, weight=None):
    v = _t_(x)
    lo, hi = (min, max) if (min != 0 or max != 0) else (float(jnp.min(v._value)), float(jnp.max(v._value)))
    hist, _ = jnp.histogram(
        v._value, bins=bins, range=(lo, hi),
        weights=None if weight is None else _t_(weight)._value,
    )
    return Tensor(hist.astype(np.int64) if weight is None else hist)


def kron(x, y):
    return apply_op(jnp.kron, _t_(x), _t_(y), name="kron")
