"""Shape / layout / indexing ops (reference: python/paddle/tensor/manipulation.py;
kernels paddle/phi/kernels/{reshape,transpose,concat,gather,...}). Static shapes
keep XLA happy: every op here has shape computable from input shapes + attrs."""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

builtins_slice = builtins.slice

from paddle_tpu.core.dtype import to_jax_dtype
from paddle_tpu.core.tensor import Tensor, apply_op

__all__ = [
    "reshape", "transpose", "concat", "stack", "split", "chunk", "squeeze",
    "unsqueeze", "flatten", "cast", "gather", "gather_nd", "scatter",
    "scatter_nd_add", "index_select", "index_sample", "tile", "expand",
    "expand_as", "broadcast_to", "flip", "roll", "where", "masked_fill",
    "take_along_axis", "put_along_axis", "topk", "sort", "argsort", "unbind",
    "numel", "slice", "strided_slice", "unstack", "repeat_interleave",
    "moveaxis", "swapaxes", "as_real", "as_complex", "crop", "pad",
    "masked_select", "nonzero", "unique", "bincount", "searchsorted",
    "tensordot", "rot90", "atleast_1d", "atleast_2d", "atleast_3d",
    "view", "view_as", "tensor_split",
]


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape)


def reshape(x, shape):
    s = _shape_list(shape)
    return apply_op(lambda v: jnp.reshape(v, s), _t(x), name="reshape")


view = reshape


def view_as(x, other):
    return reshape(x, other.shape)


def transpose(x, perm):
    p = tuple(int(i) for i in perm)
    return apply_op(lambda v: jnp.transpose(v, p), _t(x), name="transpose")


def moveaxis(x, source, destination):
    return apply_op(lambda v: jnp.moveaxis(v, source, destination), _t(x), name="moveaxis")


def swapaxes(x, axis1, axis2):
    return apply_op(lambda v: jnp.swapaxes(v, axis1, axis2), _t(x), name="swapaxes")


def concat(xs, axis=0):
    ts = [_t(x) for x in xs]
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op(lambda *vs: jnp.concatenate(vs, axis=ax), *ts, name="concat")


def stack(xs, axis=0):
    ts = [_t(x) for x in xs]
    return apply_op(lambda *vs: jnp.stack(vs, axis=int(axis)), *ts, name="stack")


def split(x, num_or_sections, axis=0):
    x = _t(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    dim = x._value.shape[ax]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        if any(s == -1 for s in sizes):
            known = sum(s for s in sizes if s != -1)
            sizes = [dim - known if s == -1 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1])

    def f(v):
        return tuple(
            jax.lax.slice_in_dim(v, int(o), int(o + s), axis=ax) for o, s in zip(offsets, sizes)
        )

    return list(apply_op(f, x, name="split"))


def tensor_split(x, num_or_indices, axis=0):
    x = _t(x)
    dim = x._value.shape[axis]
    if isinstance(num_or_indices, int):
        n = num_or_indices
        base, rem = divmod(dim, n)
        sizes = [base + (1 if i < rem else 0) for i in range(n)]
        return split(x, sizes, axis)
    idx = [0] + list(num_or_indices) + [dim]
    sizes = [idx[i + 1] - idx[i] for i in range(len(idx) - 1)]
    return split(x, sizes, axis)


def chunk(x, chunks, axis=0):
    return split(x, chunks, axis)


def squeeze(x, axis=None):
    x = _t(x)
    if axis is None:
        ax = tuple(i for i, s in enumerate(x._value.shape) if s == 1)
    elif isinstance(axis, (list, tuple)):
        ax = tuple(int(a) for a in axis if x._value.shape[int(a)] == 1)
    else:
        ax = (int(axis),) if x._value.shape[int(axis)] == 1 else ()
    return apply_op(lambda v: jnp.squeeze(v, ax), x, name="squeeze")


def unsqueeze(x, axis):
    if isinstance(axis, (list, tuple)):
        ax = tuple(int(a) for a in axis)
    else:
        ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op(lambda v: jnp.expand_dims(v, ax), _t(x), name="unsqueeze")


def flatten(x, start_axis=0, stop_axis=-1):
    x = _t(x)
    nd = x._value.ndim
    if nd == 0:
        return reshape(x, [1])
    sa = start_axis % nd
    ea = stop_axis % nd
    shape = list(x._value.shape)
    new_shape = shape[:sa] + [int(np.prod(shape[sa : ea + 1]))] + shape[ea + 1 :]
    return reshape(x, new_shape)


def cast(x, dtype):
    d = to_jax_dtype(dtype)
    return apply_op(lambda v: v.astype(d), _t(x), name="cast")


def numel(x):
    return Tensor(jnp.asarray(_t(x).size, np.int64))


def gather(x, index, axis=0):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op(
        lambda v, i: jnp.take(v, i.reshape(-1) if i.ndim > 1 else i, axis=ax),
        _t(x),
        _t(index),
        name="gather",
    )


def gather_nd(x, index):
    def f(v, idx):
        # index [..., k] indexes the first k dims of v
        k = idx.shape[-1]
        out = v[tuple(jnp.moveaxis(idx, -1, 0))]
        return out

    return apply_op(f, _t(x), _t(index), name="gather_nd")


def index_select(x, index, axis=0):
    return apply_op(lambda v, i: jnp.take(v, i, axis=int(axis)), _t(x), _t(index), name="index_select")


def index_sample(x, index):
    # x: [N, D], index: [N, K] -> out[n, k] = x[n, index[n, k]]
    return apply_op(
        lambda v, i: jnp.take_along_axis(v, i, axis=1), _t(x), _t(index), name="index_sample"
    )


def scatter(x, index, updates, overwrite=True):
    def f(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u)
        return v.at[i].add(u)

    return apply_op(f, _t(x), _t(index), _t(updates), name="scatter")


def scatter_nd_add(x, index, updates):
    def f(v, i, u):
        return v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)

    return apply_op(f, _t(x), _t(index), _t(updates), name="scatter_nd_add")


def take_along_axis(x, indices, axis, broadcast=True):
    return apply_op(
        lambda v, i: jnp.take_along_axis(v, i, axis=int(axis)),
        _t(x),
        _t(indices),
        name="take_along_axis",
    )


def put_along_axis(x, indices, values, axis, reduce="assign"):
    def f(v, i, u):
        u = jnp.broadcast_to(u, i.shape) if not hasattr(u, "shape") or u.shape != i.shape else u
        if reduce == "add":
            return _put_add(v, i, u, int(axis))
        return _put_set(v, i, u, int(axis))

    return apply_op(f, _t(x), _t(indices), _t(values), name="put_along_axis")


def _indices_grid(i, axis):
    idx = []
    for d in range(i.ndim):
        if d == axis:
            idx.append(i)
        else:
            shape = [1] * i.ndim
            shape[d] = i.shape[d]
            idx.append(jnp.broadcast_to(jnp.arange(i.shape[d]).reshape(shape), i.shape))
    return tuple(idx)


def _put_set(v, i, u, axis):
    return v.at[_indices_grid(i, axis)].set(u)


def _put_add(v, i, u, axis):
    return v.at[_indices_grid(i, axis)].add(u)


def tile(x, repeat_times):
    r = _shape_list(repeat_times)
    return apply_op(lambda v: jnp.tile(v, r), _t(x), name="tile")


def expand(x, shape):
    s = _shape_list(shape)
    x = _t(x)
    xs = list(x._value.shape)
    out = [xs[i - (len(s) - len(xs))] if v == -1 else v for i, v in enumerate(s)]
    return apply_op(lambda v: jnp.broadcast_to(v, tuple(out)), x, name="expand")


def expand_as(x, y):
    return expand(x, y.shape)


def broadcast_to(x, shape):
    return expand(x, shape)


def flip(x, axis):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (int(axis),)
    return apply_op(lambda v: jnp.flip(v, ax), _t(x), name="flip")


def roll(x, shifts, axis=None):
    return apply_op(lambda v: jnp.roll(v, shifts, axis=axis), _t(x), name="roll")


def where(condition, x=None, y=None):
    cond = _t(condition)
    if x is None and y is None:
        return nonzero(cond, as_tuple=False)
    return apply_op(lambda c, a, b: jnp.where(c, a, b), cond, _t(x), _t(y), name="where")


def masked_fill(x, mask, value):
    val = value.item() if isinstance(value, Tensor) else value
    return apply_op(lambda v, m: jnp.where(m, val, v), _t(x), _t(mask), name="masked_fill")


def masked_select(x, mask):
    # dynamic output shape -> host sync (documented; XLA needs static shapes)
    xv = np.asarray(x._value)
    mv = np.asarray(mask._value)
    return Tensor(jnp.asarray(xv[mv]))


def nonzero(x, as_tuple=False):
    v = np.asarray(_t(x)._value)
    nz = np.nonzero(v)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(a.astype(np.int64))) for a in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    v = np.asarray(_t(x)._value)
    res = np.unique(
        v, return_index=return_index, return_inverse=return_inverse,
        return_counts=return_counts, axis=axis,
    )
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def bincount(x, weights=None, minlength=0):
    if weights is None:
        return apply_op(
            lambda v: jnp.bincount(v, minlength=minlength, length=max(minlength, int(np.asarray(x._value).max(initial=0)) + 1)),
            _t(x),
            name="bincount",
        )
    return apply_op(
        lambda v, w: jnp.bincount(v, weights=w, minlength=minlength, length=max(minlength, int(np.asarray(x._value).max(initial=0)) + 1)),
        _t(x),
        _t(weights),
        name="bincount",
    )


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    d = np.int32 if out_int32 else np.int64
    return apply_op(
        lambda s, v: jnp.searchsorted(s, v, side=side).astype(d),
        _t(sorted_sequence),
        _t(values),
        name="searchsorted",
    )


def topk(x, k, axis=-1, largest=True, sorted=True):
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)

    def f(v):
        if largest:
            if axis in (-1, v.ndim - 1):
                vals, idx = jax.lax.top_k(v, kk)
            else:
                vm = jnp.moveaxis(v, axis, -1)
                vals, idx = jax.lax.top_k(vm, kk)
                vals = jnp.moveaxis(vals, -1, axis)
                idx = jnp.moveaxis(idx, -1, axis)
        else:
            idx = jnp.argsort(v, axis=axis)
            idx = jnp.take(idx, jnp.arange(kk), axis=axis)
            vals = jnp.take_along_axis(v, idx, axis=axis)
        return vals, idx.astype(np.int64)

    return apply_op(f, _t(x), name="topk")


def sort(x, axis=-1, descending=False):
    def f(v):
        out = jnp.sort(v, axis=axis)
        return jnp.flip(out, axis) if descending else out

    return apply_op(f, _t(x), name="sort")


def argsort(x, axis=-1, descending=False):
    def f(v):
        idx = jnp.argsort(v, axis=axis)
        return (jnp.flip(idx, axis) if descending else idx).astype(np.int64)

    return apply_op(f, _t(x), name="argsort")


def unbind(x, axis=0):
    x = _t(x)
    n = x._value.shape[axis]

    def f(v):
        return tuple(jnp.squeeze(s, axis) for s in jnp.split(v, n, axis=axis))

    return list(apply_op(f, x, name="unbind"))


unstack = unbind


def slice(x, axes, starts, ends):
    x = _t(x)
    shape = x._value.shape
    idx = [builtins_slice(None)] * len(shape)
    for ax, st, en in zip(axes, starts, ends):
        st = int(st.item()) if isinstance(st, Tensor) else int(st)
        en = int(en.item()) if isinstance(en, Tensor) else int(en)
        idx[ax] = builtins_slice(st, en)
    tid = tuple(idx)
    return apply_op(lambda v: v[tid], x, name="slice")


def strided_slice(x, axes, starts, ends, strides):
    x = _t(x)
    idx = [builtins_slice(None)] * x._value.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        idx[ax] = builtins_slice(int(st), int(en), int(sr))
    tid = tuple(idx)
    return apply_op(lambda v: v[tid], x, name="strided_slice")


def repeat_interleave(x, repeats, axis=None):
    return apply_op(lambda v: jnp.repeat(v, repeats, axis=axis), _t(x), name="repeat_interleave")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    x = _t(x)
    nd = x._value.ndim
    pad = _shape_list(pad)
    if len(pad) == 2 * nd:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle semantics: pad applies to the last len(pad)//2 spatial dims,
        # ordered innermost-first for NCHW
        cfg = [(0, 0)] * nd
        np_ = len(pad) // 2
        if data_format in ("NCHW", "NCL", "NCDHW"):
            dims = list(range(nd - np_, nd))
        else:
            dims = list(range(1, 1 + np_))
        for i, d in enumerate(reversed(dims)):
            cfg[d] = (pad[2 * i], pad[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]

    def f(v):
        if jmode == "constant":
            return jnp.pad(v, cfg, mode="constant", constant_values=value)
        return jnp.pad(v, cfg, mode=jmode)

    return apply_op(f, x, name="pad")


def crop(x, shape=None, offsets=None):
    x = _t(x)
    shape = _shape_list(shape)
    offsets = _shape_list(offsets) if offsets is not None else (0,) * len(shape)
    idx = tuple(builtins_slice(o, o + s) for o, s in zip(offsets, shape))
    return apply_op(lambda v: v[idx], x, name="crop")


def tensordot(x, y, axes=2):
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=axes), _t(x), _t(y), name="tensordot")


def rot90(x, k=1, axes=(0, 1)):
    return apply_op(lambda v: jnp.rot90(v, k, axes), _t(x), name="rot90")


def atleast_1d(*xs):
    outs = [apply_op(jnp.atleast_1d, _t(x), name="atleast_1d") for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*xs):
    outs = [apply_op(jnp.atleast_2d, _t(x), name="atleast_2d") for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*xs):
    outs = [apply_op(jnp.atleast_3d, _t(x), name="atleast_3d") for x in xs]
    return outs[0] if len(outs) == 1 else outs


def as_real(x):
    return apply_op(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), _t(x), name="as_real")


def as_complex(x):
    return apply_op(lambda v: jax.lax.complex(v[..., 0], v[..., 1]), _t(x), name="as_complex")
