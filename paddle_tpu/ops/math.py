"""Elementwise / unary / binary math ops (reference: python/paddle/tensor/math.py,
kernels under paddle/phi/kernels/elementwise_*, activation kernels). Each op is a
pure jax fn dispatched through apply_op so autograd comes from jax.vjp."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor, apply_op

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder", "mod",
    "pow", "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt", "rsqrt",
    "abs", "neg", "sign", "square", "reciprocal", "floor", "ceil", "round",
    "trunc", "sin", "cos", "tan", "asin", "acos", "atan", "atan2", "sinh",
    "cosh", "tanh", "asinh", "acosh", "atanh", "erf", "erfinv", "maximum",
    "minimum", "fmax", "fmin", "clip", "scale", "lerp", "isnan", "isinf",
    "isfinite", "nan_to_num", "logaddexp", "logit", "hypot", "deg2rad",
    "rad2deg", "frac", "multiply_", "add_", "scale_", "clip_", "increment",
    "stanh", "rsqrt_", "angle", "conj", "real", "imag",
]


def _binop(fn, name):
    def op(x, y, out_name=None):
        if not isinstance(x, Tensor):
            x = Tensor(jnp.asarray(x))
        if not isinstance(y, Tensor):
            y = Tensor(jnp.asarray(y, x._value.dtype) if np.isscalar(y) else jnp.asarray(y))
        return apply_op(fn, x, y, name=name)

    op.__name__ = name
    return op


def _unop(fn, name):
    def op(x):
        if not isinstance(x, Tensor):
            x = Tensor(jnp.asarray(x))
        return apply_op(fn, x, name=name)

    op.__name__ = name
    return op


add = _binop(jnp.add, "add")
subtract = _binop(jnp.subtract, "subtract")
multiply = _binop(jnp.multiply, "multiply")
divide = _binop(jnp.divide, "divide")
floor_divide = _binop(jnp.floor_divide, "floor_divide")
remainder = _binop(jnp.remainder, "remainder")
mod = remainder
maximum = _binop(jnp.maximum, "maximum")
minimum = _binop(jnp.minimum, "minimum")
fmax = _binop(jnp.fmax, "fmax")
fmin = _binop(jnp.fmin, "fmin")
atan2 = _binop(jnp.arctan2, "atan2")
logaddexp = _binop(jnp.logaddexp, "logaddexp")
hypot = _binop(jnp.hypot, "hypot")

exp = _unop(jnp.exp, "exp")
expm1 = _unop(jnp.expm1, "expm1")
log = _unop(jnp.log, "log")
log2 = _unop(jnp.log2, "log2")
log10 = _unop(jnp.log10, "log10")
log1p = _unop(jnp.log1p, "log1p")
sqrt = _unop(jnp.sqrt, "sqrt")
rsqrt = _unop(jax.lax.rsqrt, "rsqrt")
abs = _unop(jnp.abs, "abs")
neg = _unop(jnp.negative, "neg")
sign = _unop(jnp.sign, "sign")
square = _unop(jnp.square, "square")
reciprocal = _unop(jnp.reciprocal, "reciprocal")
floor = _unop(jnp.floor, "floor")
ceil = _unop(jnp.ceil, "ceil")
round = _unop(jnp.round, "round")
trunc = _unop(jnp.trunc, "trunc")
sin = _unop(jnp.sin, "sin")
cos = _unop(jnp.cos, "cos")
tan = _unop(jnp.tan, "tan")
asin = _unop(jnp.arcsin, "asin")
acos = _unop(jnp.arccos, "acos")
atan = _unop(jnp.arctan, "atan")
sinh = _unop(jnp.sinh, "sinh")
cosh = _unop(jnp.cosh, "cosh")
tanh = _unop(jnp.tanh, "tanh")
asinh = _unop(jnp.arcsinh, "asinh")
acosh = _unop(jnp.arccosh, "acosh")
atanh = _unop(jnp.arctanh, "atanh")
erf = _unop(jax.scipy.special.erf, "erf")
erfinv = _unop(jax.scipy.special.erfinv, "erfinv")
isnan = _unop(jnp.isnan, "isnan")
isinf = _unop(jnp.isinf, "isinf")
isfinite = _unop(jnp.isfinite, "isfinite")
deg2rad = _unop(jnp.deg2rad, "deg2rad")
rad2deg = _unop(jnp.rad2deg, "rad2deg")
angle = _unop(jnp.angle, "angle")
conj = _unop(jnp.conj, "conj")
real = _unop(jnp.real, "real")
imag = _unop(jnp.imag, "imag")


def frac(x):
    return apply_op(lambda v: v - jnp.trunc(v), x, name="frac")


def pow(x, y):
    if isinstance(y, Tensor):
        return apply_op(jnp.power, x, y, name="pow")
    return apply_op(lambda v: jnp.power(v, y), x, name="pow")


def clip(x, min=None, max=None):
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return apply_op(lambda v: jnp.clip(v, mn, mx), x, name="clip")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    s, b = float(scale), float(bias)

    def f(v):
        out = v * s + b if bias_after_scale else (v + b) * s
        return out

    return apply_op(f, x, name="scale")


def lerp(x, y, weight):
    if isinstance(weight, Tensor):
        return apply_op(lambda a, b, w: a + w * (b - a), x, y, weight, name="lerp")
    return apply_op(lambda a, b: a + weight * (b - a), x, y, name="lerp")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return apply_op(
        lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf), x, name="nan_to_num"
    )


def logit(x, eps=None):
    def f(v):
        u = jnp.clip(v, eps, 1 - eps) if eps else v
        return jnp.log(u / (1 - u))

    return apply_op(f, x, name="logit")


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return apply_op(lambda v: scale_b * jnp.tanh(scale_a * v), x, name="stanh")


# ---- in-place variants (paddle `op_` convention): swap the buffer -------
def _inplace(op):
    def f(x, *args, **kwargs):
        out = op(x, *args, **kwargs)
        x._set_value(out._value)
        x._grad_node = out._grad_node
        x._output_index = out._output_index
        x.stop_gradient = out.stop_gradient
        return x

    return f


add_ = _inplace(add)
multiply_ = _inplace(multiply)
scale_ = _inplace(scale)
clip_ = _inplace(clip)
rsqrt_ = _inplace(rsqrt)


def increment(x, value=1.0):
    x._set_value(x._value + value)
    return x
