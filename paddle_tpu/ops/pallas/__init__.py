from paddle_tpu.ops.pallas.rmsnorm_kernel import rmsnorm  # noqa: F401
