from paddle_tpu.ops.pallas.rmsnorm_kernel import rmsnorm  # noqa: F401
from paddle_tpu.ops.pallas.fused_ce import (  # noqa: F401
    fused_linear_cross_entropy_loss, softmax_cross_entropy_loss,
)
from paddle_tpu.ops.pallas.grouped_matmul import (  # noqa: F401
    expected_visit_counts, grouped_matmul, grouped_matmul_visit_counts,
)
