"""Shared Pallas-kernel compatibility helpers."""
from __future__ import annotations

__all__ = ["x64_off"]


def x64_off():
    """x64 mode (paddle int64 parity, enabled at package import) makes Pallas
    index maps emit i64 constants Mosaic can't legalize. `jax.enable_x64` was
    removed upstream; `jax.experimental.disable_x64` is the surviving
    spelling of the same trace-local override."""
    from jax.experimental import disable_x64

    return disable_x64()
