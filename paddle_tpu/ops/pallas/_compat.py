"""Shared Pallas-kernel compatibility helpers."""
from __future__ import annotations

import contextlib

__all__ = ["x64_off", "kernel_trace_ctx"]


def x64_off():
    """x64 mode (paddle int64 parity, enabled at package import) makes Pallas
    index maps emit i64 constants Mosaic can't legalize. `jax.enable_x64` was
    removed upstream; `jax.experimental.disable_x64` is the surviving
    spelling of the same trace-local override."""
    from jax.experimental import disable_x64

    return disable_x64()


def kernel_trace_ctx(interpret: bool):
    """Context for tracing a pallas_call: `x64_off()` on the Mosaic path,
    a no-op in interpret mode.

    Interpret mode must trace under the ambient x64 setting: when the call
    sits inside an outer `jax.jit`, its grid/loop machinery is lowered only
    when the OUTER program lowers — after this context has exited — and a
    jaxpr traced x32 but lowered x64 re-canonicalizes weak int literals into
    i64/i32 StableHLO verifier mismatches. Mosaic never defers past the
    context (and needs x64 off for its index types), so the TPU path keeps
    the override."""
    return contextlib.nullcontext() if interpret else x64_off()
