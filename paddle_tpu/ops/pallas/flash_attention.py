"""Pallas flash attention for TPU (forward + backward kernels, native GQA).

Reference analog: the vendored FlashAttention-2 CUDA kernels
(third_party/flashattn; phi/kernels/gpu/flash_attn_kernel.cu) behind
nn/functional/flash_attention.py:147.

TPU-native design: online-softmax tiling in VMEM. Forward grid =
(batch*q_heads, q_blocks); K/V stream through VMEM blocks; running (max,
denom) carried in fp32; the causal variant skips K blocks strictly above the
diagonal. Forward emits the logsumexp row stats; backward is the standard
flash-2 recurrence in two blocked kernels:

  * dq kernel — grid (BHq, q_blocks, k_blocks): dq[b,qi] accumulated in-place
    across the trailing (sequential on TPU) k-block grid dim.
  * dk/dv kernel — grid (BHkv, k_blocks, group*q_blocks): dk/dv[b,kb]
    accumulated across the trailing q-block dim, which also walks the GQA
    group so shared K/V heads see every query head.

Peak memory is O(block * D) per grid step — no [S, S] materialization in
either direction. GQA is handled by BlockSpec index maps (q-head -> kv-head
= h // group), never by materializing repeated K/V.

Falls back to interpreter mode off-TPU so the same code path is unit-tested
on CPU (the fake-device pattern, SURVEY §4.4).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas._compat import x64_off as _x64_off

try:  # pallas TPU backend may be absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["flash_attention_bshd", "flash_attention_bhsd"]

_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, causal: bool,
                scale: float, seq_len: int, block_q: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [BQ, D]
    bq = q.shape[0]

    num_kb = seq_len // block_k
    if causal:
        # process K blocks up to and including the diagonal block of this Q tile
        last = ((qi + 1) * block_q + block_k - 1) // block_k
    else:
        last = num_kb

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q_ref.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, last, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(jnp.maximum(l, 1e-30))).astype(jnp.float32)


def _validated_block(v, which, seq_len, prefix="flash_block"):
    v = int(v)
    if v <= 0 or seq_len % min(v, seq_len) != 0:
        raise ValueError(
            f"FLAGS_{prefix}_{which}={v} must be a positive divisor "
            f"of seq_len={seq_len} (grid tiling would drop positions)")
    return min(v, seq_len)


def _pick_blocks(seq_len: int):
    from paddle_tpu.core.flags import flag

    bq_f, bk_f = flag("flash_block_q"), flag("flash_block_k")
    if bq_f or bk_f:
        if not (bq_f and bk_f):
            import warnings

            warnings.warn("set BOTH FLAGS_flash_block_q and "
                          "FLAGS_flash_block_k; partial override ignored")
        else:
            return (_validated_block(bq_f, "q", seq_len),
                    _validated_block(bk_f, "k", seq_len))
    # swept end-to-end on v5e at seq 2048 (round 3): (512, 1024) beats the
    # old (256, 512) default by ~7% MFU (0.725 -> 0.778)
    bq = next((b for b in (512, 256, 128) if seq_len % b == 0), seq_len)
    bk = next((b for b in (1024, 512, 128) if seq_len % b == 0), seq_len)
    return min(bq, seq_len), min(bk, seq_len)


def _pick_blocks_bwd(seq_len: int):
    """Backward kernels tile independently of the forward (different
    arithmetic intensity); FLAGS_flash_bwd_block_q/k override."""
    from paddle_tpu.core.flags import flag

    bq_f, bk_f = flag("flash_bwd_block_q"), flag("flash_bwd_block_k")
    if bq_f or bk_f:
        if not (bq_f and bk_f):
            import warnings

            warnings.warn("set BOTH FLAGS_flash_bwd_block_q and "
                          "FLAGS_flash_bwd_block_k; partial override ignored")
        else:
            return (_validated_block(bq_f, "q", seq_len, "flash_bwd_block"),
                    _validated_block(bk_f, "k", seq_len, "flash_bwd_block"))
    return _pick_blocks(seq_len)


def _flash_fwd(q, k, v, causal: bool, scale: float, group: int, interpret: bool):
    """q: [BHq, S, D]; k,v: [BHkv, S, D] with BHq == BHkv*group -> (out, lse)."""
    bh, s, d = q.shape
    block_q, block_k = _pick_blocks(s)
    grid = (bh, s // block_q)
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, causal=causal, scale=scale,
        seq_len=s, block_q=block_q,
    )
    # Mosaic lowering mishandles 64-bit index types; the kernel is pure
    # f32/bf16/i32, so trace it with x64 off regardless of the global setting.
    with _x64_off():
        out, lse = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, s, d), lambda b, i: (b // group, 0, 0)),
                pl.BlockSpec((1, s, d), lambda b, i: (b // group, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, s, d), q.dtype),
                jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v)
    return out, lse[..., 0]


# ---------------------------------------------------------------------------
# backward kernels (flash-2 recurrence from saved lse; no S^2 anywhere)
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale: float, causal: bool, block_q: int, block_k: int):
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    # causal: K blocks strictly above the diagonal contribute nothing
    needed = True
    if causal:
        needed = kb * block_k <= (qi + 1) * block_q - 1

    @pl.when(needed)
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [BQ, D]
        k_blk = k_ref[0].astype(jnp.float32)      # [BK, D]
        v_blk = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                          # [BQ, 1]
        delta = delta_ref[0]                      # [BQ, 1]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            bq = q.shape[0]
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                      # [BQ, BK]
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_ref[0] += jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *,
                scale: float, causal: bool, block_q: int, block_k: int,
                q_blocks: int):
    kb = pl.program_id(1)
    qj = pl.program_id(2)           # walks group-major over (group, q_blocks)
    qi = qj % q_blocks              # q-block index within the query head

    @pl.when(qj == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    needed = True
    if causal:
        # whole q block above the diagonal w.r.t. this k block -> no contribution
        needed = (qi + 1) * block_q - 1 >= kb * block_k

    @pl.when(needed)
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [BQ, D]
        k_blk = k_ref[0].astype(jnp.float32)      # [BK, D]
        v_blk = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            bq = q.shape[0]
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                      # [BQ, BK]
        dv_ref[0] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_ref[0] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)


def _flash_bwd(q, k, v, out, lse, do, causal: bool, scale: float, group: int,
               interpret: bool):
    """Blocked flash-2 backward. q/do/out/lse: [BHq, ...]; k/v: [BHkv, ...]."""
    bhq, s, d = q.shape
    bhkv = k.shape[0]
    block_q, block_k = _pick_blocks_bwd(s)
    q_blocks, k_blocks = s // block_q, s // block_k
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
                    keepdims=True)                       # [BHq, S, 1]
    lse3 = lse[..., None]                                # [BHq, S, 1]

    with _x64_off():
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k),
            grid=(bhq, q_blocks, k_blocks),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((bhq, s, d), jnp.float32),
            interpret=interpret,
        )(q, k, v, do, lse3, delta)

        # trailing grid dim walks (group, q_blocks) group-major so each kv head
        # accumulates contributions from every query head in its GQA group
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k, q_blocks=q_blocks),
            grid=(bhkv, k_blocks, group * q_blocks),
            in_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda b, j, qj: (b * group + qj // q_blocks, qj % q_blocks, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, qj: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, qj: (b, j, 0)),
                pl.BlockSpec((1, block_q, d),
                             lambda b, j, qj: (b * group + qj // q_blocks, qj % q_blocks, 0)),
                pl.BlockSpec((1, block_q, 1),
                             lambda b, j, qj: (b * group + qj // q_blocks, qj % q_blocks, 0)),
                pl.BlockSpec((1, block_q, 1),
                             lambda b, j, qj: (b * group + qj // q_blocks, qj % q_blocks, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, j, qj: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, qj: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bhkv, s, d), jnp.float32),
                jax.ShapeDtypeStruct((bhkv, s, d), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v, do, lse3, delta)

    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# custom-vjp wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash3(q, k, v, causal, scale, group):
    interpret = not _on_tpu()
    out, _ = _flash_fwd(q, k, v, causal, scale, group, interpret)
    return out


def _flash3_fwd(q, k, v, causal, scale, group):
    interpret = not _on_tpu()
    out, lse = _flash_fwd(q, k, v, causal, scale, group, interpret)
    return out, (q, k, v, out, lse)


def _flash3_bwd(causal, scale, group, res, do):
    q, k, v, out, lse = res
    interpret = not _on_tpu()
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, do, causal, scale, group, interpret)
    return dq, dk, dv


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention_bhsd(q, k, v, causal: bool = False, scale: float | None = None):
    """q: [B, Hq, S, D]; k,v: [B, Hkv, S, D] with Hq % Hkv == 0 (GQA/MQA)."""
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    if hkv == 0 or hq % hkv != 0:
        raise ValueError(
            f"q heads must be a multiple of kv heads, got {hq} and {hkv}")
    group = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    q3 = q.reshape(b * hq, s, d)
    k3 = k.reshape(b * hkv, s, d)
    v3 = v.reshape(b * hkv, s, d)
    out = _flash3(q3, k3, v3, causal, scale, group)
    return out.reshape(b, hq, s, d)


def flash_attention_bshd(q, k, v, causal: bool = False, scale: float | None = None):
    """q,k,v: [B, S, H, D] (paddle flash-attention layout); GQA via H_kv < H_q."""
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    out = flash_attention_bhsd(qh, kh, vh, causal=causal, scale=scale)
    return jnp.swapaxes(out, 1, 2)
