"""Pallas flash attention for TPU.

Reference analog: the vendored FlashAttention-2 CUDA kernels
(third_party/flashattn; phi/kernels/gpu/flash_attn_kernel.cu) behind
nn/functional/flash_attention.py:147.

TPU-native design: online-softmax tiling in VMEM. Grid = (batch*heads,
q_blocks); K/V stream through VMEM blocks; running (max, denom) carried in
fp32; the causal variant skips K blocks strictly above the diagonal (work
~halves). Forward emits the logsumexp row stats so backward can rebuild P
without a second softmax pass; backward is a blocked recompute (flash-style,
no S^2 materialization in HBM thanks to XLA fusion of the masked einsums).

Falls back to interpreter mode off-TPU so the same code path is unit-tested
on CPU (the fake-device pattern, SURVEY §4.4).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend may be absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["flash_attention_bshd", "flash_attention_bhsd"]

_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int, causal: bool,
                scale: float, seq_len: int, block_q: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [BQ, D]
    bq = q.shape[0]

    num_kb = seq_len // block_k
    if causal:
        # process K blocks up to and including the diagonal block of this Q tile
        last = ((qi + 1) * block_q + block_k - 1) // block_k
    else:
        last = num_kb

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q_ref.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, last, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(jnp.maximum(l, 1e-30))).astype(jnp.float32)


def _pick_blocks(seq_len: int):
    bq = 256 if seq_len % 256 == 0 else (128 if seq_len % 128 == 0 else seq_len)
    bk = 512 if seq_len % 512 == 0 else (128 if seq_len % 128 == 0 else seq_len)
    return min(bq, seq_len), min(bk, seq_len)


def _flash_fwd(q, k, v, causal: bool, scale: float, interpret: bool):
    """q,k,v: [BH, S, D] -> (out [BH,S,D], lse [BH,S])."""
    bh, s, d = q.shape
    block_q, block_k = _pick_blocks(s)
    grid = (bh, s // block_q)
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, causal=causal, scale=scale,
        seq_len=s, block_q=block_q,
    )
    # Mosaic lowering mishandles 64-bit index types; the kernel is pure
    # f32/bf16/i32, so trace it with x64 off regardless of the global setting.
    with jax.enable_x64(False):
        out, lse = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, s, d), q.dtype),
                jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v)
    return out, lse[..., 0]


def _bwd_xla(q, k, v, out, lse, do, causal: bool, scale: float):
    """Flash-style backward from saved lse (XLA-fused; fp32 accumulation)."""
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    of = out.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * scale
    if causal:
        qpos = jnp.arange(q.shape[1])[:, None]
        kpos = jnp.arange(k.shape[1])[None, :]
        s = jnp.where(qpos >= kpos, s, _NEG_INF)
    p = jnp.exp(s - lse[..., None])
    dv = jnp.einsum("bqk,bqd->bkd", p, dof)
    dp = jnp.einsum("bqd,bkd->bqk", dof, vf)
    delta = jnp.sum(dof * of, axis=-1, keepdims=True)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf)
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash3(q, k, v, causal, scale):
    interpret = not _on_tpu()
    out, _ = _flash_fwd(q, k, v, causal, scale, interpret)
    return out


def _flash3_fwd(q, k, v, causal, scale):
    interpret = not _on_tpu()
    out, lse = _flash_fwd(q, k, v, causal, scale, interpret)
    return out, (q, k, v, out, lse)


def _flash3_bwd(causal, scale, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _bwd_xla(q, k, v, out, lse, do, causal, scale)
    return dq, dk, dv


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention_bhsd(q, k, v, causal: bool = False, scale: float | None = None):
    """q,k,v: [B, H, S, D]."""
    b, h, s, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    q3 = q.reshape(b * h, s, d)
    k3 = k.reshape(b * h, s, d)
    v3 = v.reshape(b * h, s, d)
    out = _flash3(q3, k3, v3, causal, scale)
    return out.reshape(b, h, s, d)


def flash_attention_bshd(q, k, v, causal: bool = False, scale: float | None = None):
    """q,k,v: [B, S, H, D] (paddle flash-attention layout)."""
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    out = flash_attention_bhsd(qh, kh, vh, causal=causal, scale=scale)
    return jnp.swapaxes(out, 1, 2)
