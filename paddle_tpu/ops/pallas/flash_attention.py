"""Pallas flash attention for TPU (forward + backward kernels, native GQA,
segment-aware block-sparse masking for packed sequences).

Reference analog: the vendored FlashAttention-2 CUDA kernels
(third_party/flashattn; phi/kernels/gpu/flash_attn_kernel.cu) behind
nn/functional/flash_attention.py:147.

TPU-native design: online-softmax tiling in VMEM. Forward grid =
(batch*q_heads, q_blocks); K/V stream through VMEM blocks; running (max,
denom) carried in fp32; the causal variant skips K blocks strictly above the
diagonal. Forward emits the logsumexp row stats; backward is the standard
flash-2 recurrence in two blocked kernels:

  * dq kernel — grid (BHq, q_blocks, k_blocks): dq[b,qi] accumulated in-place
    across the trailing (sequential on TPU) k-block grid dim.
  * dk/dv kernel — grid (BHkv, k_blocks, group*q_blocks): dk/dv[b,kb]
    accumulated across the trailing q-block dim, which also walks the GQA
    group so shared K/V heads see every query head.

Sequence packing (`segment_ids`, [B, S] int32): attention is block-diagonal
per document. Inside a block the kernel masks `q_seg[i] != k_seg[j]` at the
same point the causal mask applies; ACROSS blocks it skips any K block whose
segment-id range cannot intersect the Q block's (per-block min/max — packed
rows carry non-decreasing segment ids so ranges are tight), composed with the
causal diagonal skip. Per-document attention cost is therefore
O(sum_i len_i^2), not O(S^2). All three kernels (fwd, dq, dkv) share ONE
skip predicate, `_seg_blocks_can_touch`; `segment_block_visit_counts` runs
that same predicate as a standalone Pallas kernel so benchmarks can count
exactly which K blocks the attention kernels visit.

Peak memory is O(block * D) per grid step — no [S, S] materialization in
either direction. GQA is handled by BlockSpec index maps (q-head -> kv-head
= h // group), never by materializing repeated K/V.

Falls back to interpreter mode off-TPU so the same code path is unit-tested
on CPU (the fake-device pattern, SURVEY §4.4); `force_interpret()` pins that
mode explicitly (the conftest fixture the tier-1 segment tests use).
"""
from __future__ import annotations

import functools
import math
import threading
from contextlib import contextmanager

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas._compat import kernel_trace_ctx as _kernel_trace_ctx

try:  # pallas TPU backend may be absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["flash_attention_bshd", "flash_attention_bhsd",
           "segment_block_visit_counts", "pallas_blocks_ok",
           "force_interpret"]

_NEG_INF = -1e30


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


class _InterpretTLS(threading.local):
    def __init__(self):
        self.force = False


_interp_tls = _InterpretTLS()


@contextmanager
def force_interpret():
    """Run the Pallas kernels in interpret mode regardless of platform — the
    hardware-free path the tier-1 suite uses to exercise the exact kernel
    code (incl. the segment block-skip predicate) the TPU runs."""
    prev = _interp_tls.force
    _interp_tls.force = True
    try:
        yield
    finally:
        _interp_tls.force = prev


def _interpret_mode() -> bool:
    return _interp_tls.force or not _on_tpu()


def interpret_forced() -> bool:
    """True inside a `force_interpret()` block — callers with their own XLA
    fallback (F.scaled_dot_product_attention) route into the Pallas kernels
    off-TPU only when the tests ask for it explicitly."""
    return _interp_tls.force


def _seg_blocks_can_touch(q_min, q_max, k_min, k_max):
    """THE cross-block skip predicate: a K block may contribute to a Q block
    only if their segment-id RANGES intersect (conservative for arbitrary
    ids; exact for the packer's per-row non-decreasing ids). Shared by the
    forward, dq, and dk/dv kernels and by the visit-count kernel, so the
    benchmark counter provably counts what the attention kernels execute."""
    return jnp.logical_and(k_min <= q_max, k_max >= q_min)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, *rest, block_k: int, causal: bool,
                scale: float, seq_len: int, block_q: int, segmented: bool,
                block_skip: bool):
    if segmented:
        qseg_ref, kseg_ref, o_ref, lse_ref = rest
    else:
        qseg_ref = kseg_ref = None
        o_ref, lse_ref = rest
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [BQ, D]
    bq = q.shape[0]
    if segmented:
        q_seg = qseg_ref[...]                       # [1, BQ] int32
        q_seg_col = q_seg.reshape(bq, 1)
        q_min = jnp.min(q_seg)
        q_max = jnp.max(q_seg)

    num_kb = seq_len // block_k
    if causal:
        # process K blocks up to and including the diagonal block of this Q tile
        last = ((qi + 1) * block_q + block_k - 1) // block_k
    else:
        last = num_kb

    def compute(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [BQ, BK]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        if segmented:
            k_seg_blk = kseg_ref[:, pl.ds(kb * block_k, block_k)]  # [1, BK]
            s = jnp.where(q_seg_col == k_seg_blk, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l, acc

    if segmented and block_skip:
        def body(kb, carry):
            k_seg_blk = kseg_ref[:, pl.ds(kb * block_k, block_k)]
            needed = _seg_blocks_can_touch(q_min, q_max,
                                           jnp.min(k_seg_blk),
                                           jnp.max(k_seg_blk))
            return jax.lax.cond(needed, lambda c: compute(kb, c),
                                lambda c: c, carry)
    else:
        body = compute

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q_ref.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, last, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(jnp.maximum(l, 1e-30))).astype(jnp.float32)


def _validated_block(v, which, seq_len, prefix="flash_block"):
    v = int(v)
    if v <= 0 or seq_len % min(v, seq_len) != 0:
        raise ValueError(
            f"FLAGS_{prefix}_{which}={v} must be a positive divisor "
            f"of seq_len={seq_len} (grid tiling would drop positions)")
    return min(v, seq_len)


def _heuristic_blocks(seq_len: int):
    # swept end-to-end on v5e at seq 2048 (round 3): (512, 1024) beats the
    # old (256, 512) default by ~7% MFU (0.725 -> 0.778)
    bq = next((b for b in (512, 256, 128) if seq_len % b == 0), seq_len)
    bk = next((b for b in (1024, 512, 128) if seq_len % b == 0), seq_len)
    return min(bq, seq_len), min(bk, seq_len)


def _make_validate(seq_len: int, prefix: str):
    def validate(values, geometry):
        _validated_block(values["block_q"], "q", seq_len, prefix)
        _validated_block(values["block_k"], "k", seq_len, prefix)

    return validate


def _pick_blocks(seq_len: int):
    """Forward Q/K tiles through the shared resolver (FLAGS override >
    tuning-cache hit > heuristic; the once-duplicated partial-override
    warn branch now lives in tuning.blocks.resolve_blocks)."""
    from paddle_tpu.tuning.blocks import resolve_blocks

    res = resolve_blocks("flash_fwd", {"seq_len": seq_len},
                         default=lambda g: _heuristic_blocks(seq_len),
                         validate=_make_validate(seq_len, "flash_block"))
    bq, bk = res.as_tuple()
    return min(bq, seq_len), min(bk, seq_len)


def _pick_blocks_bwd(seq_len: int):
    """Backward kernels tile independently of the forward (different
    arithmetic intensity); FLAGS_flash_bwd_block_q/k override, tuned
    'flash_bwd' entries next, forward picks as the default."""
    from paddle_tpu.tuning.blocks import resolve_blocks

    res = resolve_blocks("flash_bwd", {"seq_len": seq_len},
                         default=lambda g: _pick_blocks(seq_len),
                         validate=_make_validate(seq_len,
                                                 "flash_bwd_block"))
    bq, bk = res.as_tuple()
    return min(bq, seq_len), min(bk, seq_len)


def pallas_blocks_ok(seq_len: int):
    """(ok, reason): validate that the flag-chosen forward AND backward block
    sizes divide `seq_len`. Callers with an XLA fallback (e.g.
    F.scaled_dot_product_attention) check this BEFORE entering Pallas so a
    bad FLAGS_flash_block_q/k override degrades to the fallback with a
    warning instead of failing inside the kernel launch."""
    try:
        _pick_blocks(seq_len)
        _pick_blocks_bwd(seq_len)
        return True, None
    except ValueError as e:
        return False, str(e)


def _block_skip_enabled() -> bool:
    from paddle_tpu.core.flags import flag

    try:
        return bool(flag("flash_segment_block_skip"))
    except KeyError:  # pragma: no cover - flags module always defines it
        return True


def _flash_fwd(q, k, v, seg, causal: bool, scale: float, group: int,
               heads_q: int, interpret: bool):
    """q: [BHq, S, D]; k,v: [BHkv, S, D] with BHq == BHkv*group;
    seg: [B, S] int32 or None -> (out, lse)."""
    bh, s, d = q.shape
    block_q, block_k = _pick_blocks(s)
    grid = (bh, s // block_q)
    segmented = seg is not None
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, causal=causal, scale=scale,
        seq_len=s, block_q=block_q, segmented=segmented,
        block_skip=_block_skip_enabled(),
    )
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, s, d), lambda b, i: (b // group, 0, 0)),
        pl.BlockSpec((1, s, d), lambda b, i: (b // group, 0, 0)),
    ]
    args = [q, k, v]
    if segmented:
        in_specs.append(pl.BlockSpec((1, block_q),
                                     lambda b, i: (b // heads_q, i)))
        in_specs.append(pl.BlockSpec((1, s), lambda b, i: (b // heads_q, 0)))
        args.extend([seg, seg])
    # Mosaic lowering mishandles 64-bit index types; the kernel is pure
    # f32/bf16/i32, so trace it with x64 off regardless of the global setting.
    # Interpret mode keeps the ambient x64 (see kernel_trace_ctx): an outer
    # jit lowers the grid loops after this context exits, and an x32-traced /
    # x64-lowered jaxpr trips the StableHLO verifier on weak int literals.
    with _kernel_trace_ctx(interpret):
        out, lse = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, s, d), q.dtype),
                jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
            ],
            interpret=interpret,
        )(*args)
    return out, lse[..., 0]


# ---------------------------------------------------------------------------
# backward kernels (flash-2 recurrence from saved lse; no S^2 anywhere)
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               scale: float, causal: bool, block_q: int, block_k: int,
               segmented: bool, block_skip: bool):
    if segmented:
        qseg_ref, kseg_ref, dq_ref = rest
    else:
        qseg_ref = kseg_ref = None
        (dq_ref,) = rest
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    # causal: K blocks strictly above the diagonal contribute nothing;
    # segments: K blocks whose id range misses the Q block's contribute
    # nothing either (the SAME predicate the forward skip uses)
    needed = True
    if causal:
        needed = kb * block_k <= (qi + 1) * block_q - 1
    if segmented and block_skip:
        seg_ok = _seg_blocks_can_touch(
            jnp.min(qseg_ref[...]), jnp.max(qseg_ref[...]),
            jnp.min(kseg_ref[...]), jnp.max(kseg_ref[...]))
        needed = jnp.logical_and(needed, seg_ok)

    @pl.when(needed)
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [BQ, D]
        k_blk = k_ref[0].astype(jnp.float32)      # [BK, D]
        v_blk = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                          # [BQ, 1]
        delta = delta_ref[0]                      # [BQ, 1]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        bq = q.shape[0]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        if segmented:
            s = jnp.where(qseg_ref[...].reshape(bq, 1) == kseg_ref[...],
                          s, _NEG_INF)
        p = jnp.exp(s - lse)                      # [BQ, BK]
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_ref[0] += jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                scale: float, causal: bool, block_q: int, block_k: int,
                q_blocks: int, segmented: bool, block_skip: bool):
    if segmented:
        qseg_ref, kseg_ref, dk_ref, dv_ref = rest
    else:
        qseg_ref = kseg_ref = None
        dk_ref, dv_ref = rest
    kb = pl.program_id(1)
    qj = pl.program_id(2)           # walks group-major over (group, q_blocks)
    qi = qj % q_blocks              # q-block index within the query head

    @pl.when(qj == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    needed = True
    if causal:
        # whole q block above the diagonal w.r.t. this k block -> no contribution
        needed = (qi + 1) * block_q - 1 >= kb * block_k
    if segmented and block_skip:
        seg_ok = _seg_blocks_can_touch(
            jnp.min(qseg_ref[...]), jnp.max(qseg_ref[...]),
            jnp.min(kseg_ref[...]), jnp.max(kseg_ref[...]))
        needed = jnp.logical_and(needed, seg_ok)

    @pl.when(needed)
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [BQ, D]
        k_blk = k_ref[0].astype(jnp.float32)      # [BK, D]
        v_blk = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        bq = q.shape[0]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        if segmented:
            s = jnp.where(qseg_ref[...].reshape(bq, 1) == kseg_ref[...],
                          s, _NEG_INF)
        p = jnp.exp(s - lse)                      # [BQ, BK]
        dv_ref[0] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_ref[0] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)


def _flash_bwd(q, k, v, seg, out, lse, do, causal: bool, scale: float,
               group: int, heads_q: int, interpret: bool):
    """Blocked flash-2 backward. q/do/out/lse: [BHq, ...]; k/v: [BHkv, ...];
    seg: [B, S] int32 or None."""
    bhq, s, d = q.shape
    bhkv = k.shape[0]
    heads_kv = heads_q // group
    block_q, block_k = _pick_blocks_bwd(s)
    q_blocks, k_blocks = s // block_q, s // block_k
    segmented = seg is not None
    block_skip = _block_skip_enabled()
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
                    keepdims=True)                       # [BHq, S, 1]
    lse3 = lse[..., None]                                # [BHq, S, 1]

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // group, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
    ]
    dq_args = [q, k, v, do, lse3, delta]
    if segmented:
        dq_in_specs.append(pl.BlockSpec(
            (1, block_q), lambda b, i, j: (b // heads_q, i)))
        dq_in_specs.append(pl.BlockSpec(
            (1, block_k), lambda b, i, j: (b // heads_q, j)))
        dq_args.extend([seg, seg])

    with _kernel_trace_ctx(interpret):
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k,
                              segmented=segmented, block_skip=block_skip),
            grid=(bhq, q_blocks, k_blocks),
            in_specs=dq_in_specs,
            out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((bhq, s, d), jnp.float32),
            interpret=interpret,
        )(*dq_args)

        # trailing grid dim walks (group, q_blocks) group-major so each kv head
        # accumulates contributions from every query head in its GQA group
        dkv_in_specs = [
            pl.BlockSpec((1, block_q, d),
                         lambda b, j, qj: (b * group + qj // q_blocks, qj % q_blocks, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, qj: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, qj: (b, j, 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda b, j, qj: (b * group + qj // q_blocks, qj % q_blocks, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda b, j, qj: (b * group + qj // q_blocks, qj % q_blocks, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda b, j, qj: (b * group + qj // q_blocks, qj % q_blocks, 0)),
        ]
        dkv_args = [q, k, v, do, lse3, delta]
        if segmented:
            dkv_in_specs.append(pl.BlockSpec(
                (1, block_q),
                lambda b, j, qj: (b // heads_kv, qj % q_blocks)))
            dkv_in_specs.append(pl.BlockSpec(
                (1, block_k), lambda b, j, qj: (b // heads_kv, j)))
            dkv_args.extend([seg, seg])
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k,
                              q_blocks=q_blocks, segmented=segmented,
                              block_skip=block_skip),
            grid=(bhkv, k_blocks, group * q_blocks),
            in_specs=dkv_in_specs,
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, j, qj: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, j, qj: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bhkv, s, d), jnp.float32),
                jax.ShapeDtypeStruct((bhkv, s, d), jnp.float32),
            ],
            interpret=interpret,
        )(*dkv_args)

    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# block-visit counter (the bench/test proof of the sparsity claim)
# ---------------------------------------------------------------------------

def _visit_kernel(seg_ref, cnt_ref, *, block_q: int, block_k: int,
                  seq_len: int, causal: bool):
    qi = pl.program_id(1)
    q_seg = seg_ref[:, pl.ds(qi * block_q, block_q)]
    q_min = jnp.min(q_seg)
    q_max = jnp.max(q_seg)
    num_kb = seq_len // block_k
    if causal:
        last = ((qi + 1) * block_q + block_k - 1) // block_k
    else:
        last = num_kb

    def body(kb, n):
        k_seg = seg_ref[:, pl.ds(kb * block_k, block_k)]
        needed = _seg_blocks_can_touch(q_min, q_max,
                                       jnp.min(k_seg), jnp.max(k_seg))
        return n + needed.astype(jnp.float32)

    n = jax.lax.fori_loop(0, last, body, jnp.zeros((), jnp.float32))
    cnt_ref[0, 0, 0] = n


def segment_block_visit_counts(segment_ids, block_q: int | None = None,
                               block_k: int | None = None,
                               causal: bool = True,
                               interpret: bool | None = None):
    """Per-(row, q-block) count of K blocks the segment-aware kernels VISIT,
    computed by running the forward kernel's exact skip predicate
    (`_seg_blocks_can_touch` + the causal diagonal bound) as its own Pallas
    kernel. Returns int32 [B, q_blocks]; sum()/total_blocks is the visited
    fraction the bench `packing` arm reports (~sum len_i^2 / S^2 under
    packing vs ~1/2 causal dense)."""
    seg = jnp.asarray(segment_ids, jnp.int32)
    b, s = seg.shape
    if block_q is None or block_k is None:
        bq, bk = _pick_blocks(s)
        block_q = block_q or bq
        block_k = block_k or bk
    if interpret is None:
        interpret = _interpret_mode()
    kernel = functools.partial(_visit_kernel, block_q=block_q,
                               block_k=block_k, seq_len=s, causal=causal)
    with _kernel_trace_ctx(interpret):
        cnt = pl.pallas_call(
            kernel,
            grid=(b, s // block_q),
            in_specs=[pl.BlockSpec((1, s), lambda r, i: (r, 0))],
            out_specs=pl.BlockSpec((1, 1, 1), lambda r, i: (r, i, 0)),
            out_shape=jax.ShapeDtypeStruct((b, s // block_q, 1), jnp.float32),
            interpret=interpret,
        )(seg)
    return cnt[..., 0].astype(jnp.int32)


# ---------------------------------------------------------------------------
# custom-vjp wrappers
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash3(q, k, v, causal, scale, group, interpret):
    out, _ = _flash_fwd(q, k, v, None, causal, scale, group, group, interpret)
    return out


def _flash3_fwd(q, k, v, causal, scale, group, interpret):
    out, lse = _flash_fwd(q, k, v, None, causal, scale, group, group,
                          interpret)
    return out, (q, k, v, out, lse)


def _flash3_bwd(causal, scale, group, interpret, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, None, out, lse, do, causal, scale,
                            group, group, interpret)
    return dq, dk, dv


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash3_seg(q, k, v, seg, causal, scale, group, heads_q, interpret):
    out, _ = _flash_fwd(q, k, v, seg, causal, scale, group, heads_q,
                        interpret)
    return out


def _flash3_seg_fwd(q, k, v, seg, causal, scale, group, heads_q, interpret):
    out, lse = _flash_fwd(q, k, v, seg, causal, scale, group, heads_q,
                          interpret)
    return out, (q, k, v, seg, out, lse)


def _flash3_seg_bwd(causal, scale, group, heads_q, interpret, res, do):
    q, k, v, seg, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, seg, out, lse, do, causal, scale,
                            group, heads_q, interpret)
    return dq, dk, dv, None  # integer segment ids carry no cotangent


_flash3_seg.defvjp(_flash3_seg_fwd, _flash3_seg_bwd)


def flash_attention_bhsd(q, k, v, causal: bool = False,
                         scale: float | None = None, segment_ids=None,
                         interpret: bool | None = None):
    """q: [B, Hq, S, D]; k,v: [B, Hkv, S, D] with Hq % Hkv == 0 (GQA/MQA).
    segment_ids: [B, S] int32 packed-document ids (attention is then
    block-diagonal per document, with whole K blocks skipped when no segment
    overlaps the Q block)."""
    b, hq, s, d = q.shape
    hkv = k.shape[1]
    if hkv == 0 or hq % hkv != 0:
        raise ValueError(
            f"q heads must be a multiple of kv heads, got {hq} and {hkv}")
    group = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _interpret_mode()
    q3 = q.reshape(b * hq, s, d)
    k3 = k.reshape(b * hkv, s, d)
    v3 = v.reshape(b * hkv, s, d)
    if segment_ids is None:
        out = _flash3(q3, k3, v3, causal, scale, group, interpret)
    else:
        seg = jnp.asarray(segment_ids, jnp.int32)
        if seg.shape != (b, s):
            raise ValueError(
                f"segment_ids must be [batch, seq]=({b}, {s}), "
                f"got {seg.shape}")
        out = _flash3_seg(q3, k3, v3, seg, causal, scale, group, hq,
                          interpret)
    return out.reshape(b, hq, s, d)


def flash_attention_bshd(q, k, v, causal: bool = False,
                         scale: float | None = None, segment_ids=None,
                         interpret: bool | None = None):
    """q,k,v: [B, S, H, D] (paddle flash-attention layout); GQA via H_kv < H_q."""
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    out = flash_attention_bhsd(qh, kh, vh, causal=causal, scale=scale,
                               segment_ids=segment_ids, interpret=interpret)
    return jnp.swapaxes(out, 1, 2)
