"""Fused chunked LM-head + softmax-cross-entropy (never materialize logits).

Reference analog: the fused CE kernels production LLM stacks keep next to the
head projection — Liger-kernel's fused_linear_cross_entropy and Megatron's
vocab-parallel cross entropy (reference ParallelCrossEntropy,
fleet/layers/mpu/mp_layers.py:742). At LM scale the `[tokens, vocab]` logits
tensor is the single largest HBM spike of a train step (LLaMA-2-7B at
batch*seq=4096, vocab 32000: 512 MB in fp32), and it is pure overhead — the
loss needs only three per-token scalars (max, log-sum-exp, target logit).

TPU-native design: one `jax.custom_vjp` computes
``loss = CE(x @ W + b, labels)`` in chunks so the full logits never exist in
forward OR backward:

* **token-chunked** (`variant="tokens"`): `lax.scan` over token chunks; each
  chunk materializes only a `[C, V]` logits tile in fp32, reduces it to the
  per-token stats, and is freed before the next chunk. Backward replays the
  same chunking, recomputing the tile and accumulating `dW`/`db` in fp32.
* **vocab-chunked** (`variant="vocab"`): `lax.scan` over vocab chunks with
  online (flash-style) max/sum-exp rescaling — the right shape when the
  token count is small but the vocabulary is huge.
* **pallas** (`variant="pallas"`): a Pallas kernel grids over
  (token-block, vocab-block) and keeps the running max/sum-exp/target/sum
  accumulators resident in VMEM, one MXU matmul per tile; it falls back to
  interpreter mode off-TPU (fake-device pattern, SURVEY §4.4) so tier-1 CPU
  tests exercise the identical kernel body. Backward reuses the chunked
  scan (already logits-free).

* **mp-parallel softmax**: when the "mp" mesh axis is bound (shard_map — the
  pipelined runtimes and manual-collective TP), each rank keeps only its
  vocab shard: labels shift into the local range, the per-token stats reduce
  with `pmax`/`psum` over the axis (Megatron fwd), and backward `psum`s the
  partial `dx` while `dW` stays shard-local (Megatron bwd) — no rank ever
  holds a full vocab row.

Numerics: per-chunk logits, all stats and all gradient accumulators are
fp32 regardless of input dtype (bf16-safe); label smoothing, ignore_index
and a z-loss hook (`z_loss * logsumexp^2`, the PaLM/Megatron stabilizer)
are folded into the same chunked pass so they never force the unfused path.

Exports raw-array functions; the Tensor-level surface lives in
`paddle_tpu.nn.functional` (`cross_entropy` fast path,
`parallel_cross_entropy`, `fused_linear_cross_entropy`) and
`paddle_tpu.incubate.nn.FusedLinearCrossEntropy`.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas._compat import x64_off

__all__ = ["fused_linear_cross_entropy_loss", "softmax_cross_entropy_loss",
           "resolve_chunks", "x64_off"]

_NEG_INF = float(np.finfo(np.float32).min)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _mp_info(mp_axis):
    """(axis_name, world) when `mp_axis` names a bound shard_map axis."""
    if not mp_axis:
        return None, 1
    from paddle_tpu.distributed.collective import _bound_axes

    if not _bound_axes((mp_axis,)):
        return None, 1
    return mp_axis, jax.lax.psum(1, mp_axis)


class _CECfg(NamedTuple):
    """Static (hashable) config keying one compiled custom_vjp instance."""
    ignore_index: int
    label_smoothing: float
    z_loss: float
    chunk_tokens: int
    chunk_vocab: int
    variant: str          # "tokens" | "vocab" | "pallas"
    mp_axis: str | None   # bound shard_map axis name, or None
    has_w: bool
    has_bias: bool
    # fp8_policy='matmuls+head': the head projection (and the backward
    # dx/dW matmuls, with the d-logits tile in e5m2) run through float8 with
    # current scaling; per-token softmax stats and accumulators stay fp32
    fp8: bool = False


def _check_labels(labels):
    """The unfused gather rejected float labels at trace time; keep that
    contract — astype(int32) would silently truncate them instead."""
    if not jnp.issubdtype(jnp.asarray(labels).dtype, jnp.integer):
        raise TypeError(
            "fused cross-entropy takes integer class labels, got dtype "
            f"{jnp.asarray(labels).dtype}; for probabilistic targets use "
            "soft_label=True (the unfused path)")


def resolve_chunks(n_tokens: int, vocab: int, chunk_tokens: int = 0,
                   chunk_vocab: int = 0) -> tuple[int, int]:
    """Default chunk sizes bounding the live logits tile to ~4M fp32 elements
    (16 MB — comfortably inside VMEM-adjacent working set on TPU, cheap on
    CPU). Flag/arg overrides win when positive."""
    target = 1 << 22
    ct = chunk_tokens if chunk_tokens > 0 else max(
        16, min(n_tokens, target // max(vocab, 1)))
    cv = chunk_vocab if chunk_vocab > 0 else max(
        128, min(vocab, target // max(n_tokens, 1)))
    return min(ct, max(n_tokens, 1)), min(cv, max(vocab, 1))


# ---------------------------------------------------------------------------
# per-token stats: m (running max), s (sum exp shifted), t (target logit),
# sl (sum of logits — label-smoothing mean term). All fp32, shape [N].
# ---------------------------------------------------------------------------


def _chunk_stats(logits, labels_c):
    """Stats of one fp32 logits tile [C, V_local] against local labels [C]."""
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    m = jnp.max(logits, axis=-1)
    s = jnp.sum(jnp.exp(logits - m[:, None]), axis=-1)
    hit = col == labels_c[:, None]
    t = jnp.sum(jnp.where(hit, logits, 0.0), axis=-1)
    sl = jnp.sum(logits, axis=-1)
    return m, s, t, sl


def _fp8_mm(a, b, a_e5m2=False):
    """Current-scaled fp8 matmul, fp32 out (no vjp of its own — the fused-CE
    custom_vjp owns forward AND backward, so forward tiles, the backward's
    recomputed tiles, and the dx/dW products all quantize consistently)."""
    from paddle_tpu.amp.fp8 import fp8_matmul

    return fp8_matmul(a, b,
                      a_dtype=jnp.float8_e5m2 if a_e5m2 else None)


def _project(x_c, w, b, fp8=False):
    if fp8:
        out = _fp8_mm(x_c, w)
    else:
        out = jnp.dot(x_c.astype(jnp.float32), w.astype(jnp.float32),
                      preferred_element_type=jnp.float32)
    if b is not None:
        out = out + b.astype(jnp.float32)
    return out


def _pad_tokens(x, labels, chunk):
    n = x.shape[0]
    nc = -(-n // chunk)
    pad = nc * chunk - n
    if pad:
        x = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
        labels = jnp.pad(labels, (0, pad), constant_values=-1)
    return x, labels, nc


def _stats_tokens(cfg: _CECfg, x, w, b, labels_loc):
    """Token-chunked scan. With has_w, x is [N, H] and each chunk projects
    to a [C, V] fp32 tile; without, x IS the logits and chunks are slices."""
    n = x.shape[0]
    xp, lp, nc = _pad_tokens(x, labels_loc, cfg.chunk_tokens)
    xc = xp.reshape((nc, cfg.chunk_tokens) + xp.shape[1:])
    lc = lp.reshape(nc, cfg.chunk_tokens)

    def step(_, args):
        xi, li = args
        logits = (_project(xi, w, b, cfg.fp8) if cfg.has_w
                  else xi.astype(jnp.float32))
        return None, _chunk_stats(logits, li)

    _, (m, s, t, sl) = jax.lax.scan(step, None, (xc, lc))
    return tuple(a.reshape(-1)[:n] for a in (m, s, t, sl))


def _pad_vocab(w, b, vloc, chunk):
    nc = -(-vloc // chunk)
    pad = nc * chunk - vloc
    if pad:
        w = jnp.pad(w, ((0, 0), (0, pad)))
        if b is not None:
            b = jnp.pad(b, (0, pad))
    return w, b, nc


def _stats_vocab(cfg: _CECfg, x, w, b, labels_loc):
    """Vocab-chunked scan with online max/sum-exp rescaling (flash-softmax
    recurrence) — [N, CV] tiles, never [N, V]."""
    n, vloc = x.shape[0], w.shape[1]
    cv = cfg.chunk_vocab
    wp, bp, nc = _pad_vocab(w, b, vloc, cv)
    wc = jnp.moveaxis(wp.reshape(wp.shape[0], nc, cv), 1, 0)  # [nc, H, cv]
    bc = (bp.reshape(nc, cv) if b is not None else None)
    xf = x.astype(jnp.float32)

    def step(carry, args):
        m, s, t, sl = carry
        j = args[0]
        wi = args[1]
        if cfg.fp8:
            logits = _fp8_mm(xf, wi)
        else:
            logits = jnp.dot(xf, wi.astype(jnp.float32),
                             preferred_element_type=jnp.float32)
        if bc is not None:
            logits = logits + args[2].astype(jnp.float32)
        col = j * cv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        in_v = col < vloc
        bm = jnp.max(jnp.where(in_v, logits, _NEG_INF), axis=-1)
        nm = jnp.maximum(m, bm)
        s = s * jnp.exp(m - nm) + jnp.sum(
            jnp.where(in_v, jnp.exp(logits - nm[:, None]), 0.0), axis=-1)
        t = t + jnp.sum(jnp.where(col == labels_loc[:, None], logits, 0.0),
                        axis=-1)
        sl = sl + jnp.sum(jnp.where(in_v, logits, 0.0), axis=-1)
        return (nm, s, t, sl), None

    init = (jnp.full((n,), _NEG_INF, jnp.float32), jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32))
    xs = (jnp.arange(nc, dtype=jnp.int32), wc) + ((bc,) if bc is not None else ())
    (m, s, t, sl), _ = jax.lax.scan(step, init, xs)
    return m, s, t, sl


# ---------------------------------------------------------------------------
# Pallas stats kernel: grid (token blocks, vocab blocks); running accumulators
# live in the revisited output blocks (the sequential-grid idiom the rmsnorm
# kernel's dw accumulation uses). Stats are broadcast over a 128-lane row to
# satisfy tiling; column 0 is read back.
# ---------------------------------------------------------------------------


def _ce_stats_kernel(x_ref, w_ref, lab_ref, m_ref, s_ref, t_ref, sl_ref,
                     *, bv: int, vloc: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        s_ref[...] = jnp.zeros_like(s_ref)
        t_ref[...] = jnp.zeros_like(t_ref)
        sl_ref[...] = jnp.zeros_like(sl_ref)

    logits = jnp.dot(x_ref[...].astype(jnp.float32),
                     w_ref[...].astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    col = j * bv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    in_v = col < vloc
    lab = lab_ref[:, :1]  # labels lane-replicated; column 0 is the value
    m_prev = m_ref[:, :1]
    bm = jnp.max(jnp.where(in_v, logits, _NEG_INF), axis=-1, keepdims=True)
    nm = jnp.maximum(m_prev, bm)
    s = s_ref[:, :1] * jnp.exp(m_prev - nm) + jnp.sum(
        jnp.where(in_v, jnp.exp(logits - nm), 0.0), axis=-1, keepdims=True)
    t = t_ref[:, :1] + jnp.sum(jnp.where(col == lab, logits, 0.0),
                               axis=-1, keepdims=True)
    sl = sl_ref[:, :1] + jnp.sum(jnp.where(in_v, logits, 0.0),
                                 axis=-1, keepdims=True)
    lanes = m_ref.shape[-1]
    m_ref[...] = jnp.broadcast_to(nm, (nm.shape[0], lanes))
    s_ref[...] = jnp.broadcast_to(s, (s.shape[0], lanes))
    t_ref[...] = jnp.broadcast_to(t, (t.shape[0], lanes))
    sl_ref[...] = jnp.broadcast_to(sl, (sl.shape[0], lanes))


def _stats_pallas(cfg: _CECfg, x, w, labels_loc, interpret=None):
    n, h = x.shape
    vloc = w.shape[1]
    br = min(cfg.chunk_tokens, 256, n)
    bv = min(cfg.chunk_vocab, 512, vloc)
    xp, lp, ni = _pad_tokens(x, labels_loc, br)
    wp, _, nj = _pad_vocab(w, None, vloc, bv)
    if interpret is None:
        interpret = not _on_tpu()
    kern = functools.partial(_ce_stats_kernel, bv=bv, vloc=vloc)
    stat = jax.ShapeDtypeStruct((ni * br, 128), jnp.float32)
    # labels lane-replicated to a (rows, 128) int32 tile (min int tiling)
    lab = jnp.broadcast_to(lp.astype(jnp.int32)[:, None], (ni * br, 128))
    with x64_off():
        m, s, t, sl = pl.pallas_call(
            kern,
            grid=(ni, nj),
            in_specs=[pl.BlockSpec((br, h), lambda i, j: (i, 0)),
                      pl.BlockSpec((h, bv), lambda i, j: (0, j)),
                      pl.BlockSpec((br, 128), lambda i, j: (i, 0))],
            out_specs=[pl.BlockSpec((br, 128), lambda i, j: (i, 0))] * 4,
            out_shape=[stat] * 4,
            interpret=interpret,
        )(xp, wp, lab)
    return tuple(a[:n, 0] for a in (m, s, t, sl))


# ---------------------------------------------------------------------------
# forward assembly + backward (shared by all variants)
# ---------------------------------------------------------------------------


def _local_labels(cfg: _CECfg, labels, vloc):
    """Shift labels into the local vocab shard range under bound mp; out-of-
    shard (and ignore_index) labels fall outside [0, vloc) and match nothing."""
    axis, world = _mp_info(cfg.mp_axis)
    if axis is None:
        return labels.astype(jnp.int32), None, vloc
    off = jax.lax.axis_index(axis).astype(jnp.int32) * vloc
    return labels.astype(jnp.int32) - off, axis, vloc * world


def _fwd_impl(cfg: _CECfg, x, w, b, labels):
    vloc = w.shape[1] if cfg.has_w else x.shape[-1]
    lab_loc, axis, v_total = _local_labels(cfg, labels, vloc)
    if cfg.variant == "vocab" and cfg.has_w:
        m, s, t, sl = _stats_vocab(cfg, x, w, b, lab_loc)
    elif cfg.variant == "pallas" and cfg.has_w and b is None:
        m, s, t, sl = _stats_pallas(cfg, x, w, lab_loc)
    else:
        m, s, t, sl = _stats_tokens(cfg, x, w, b, lab_loc)
    lse = m + jnp.log(s)
    if axis is not None:
        g = jax.lax.pmax(lse, axis)
        lse = g + jnp.log(jax.lax.psum(jnp.exp(lse - g), axis))
        t = jax.lax.psum(t, axis)
        sl = jax.lax.psum(sl, axis)
    eps = cfg.label_smoothing
    nll = lse - t if eps == 0.0 else lse - (1.0 - eps) * t - eps * sl / v_total
    if cfg.z_loss:
        nll = nll + cfg.z_loss * lse * lse
    valid = labels != cfg.ignore_index
    return jnp.where(valid, nll, 0.0), lse


def _bwd_coefs(cfg: _CECfg, labels, lse, ct):
    ctv = jnp.where(labels != cfg.ignore_index, ct.astype(jnp.float32), 0.0)
    coef_p = ctv * (1.0 + 2.0 * cfg.z_loss * lse) if cfg.z_loss else ctv
    return ctv, coef_p


def _chunk_dlogits(cfg: _CECfg, logits, lab_c, lse_c, ctv_c, coef_c, v_total):
    """d loss / d logits for one fp32 tile: p*coef - (1-eps)*ct*onehot -
    (eps/V)*ct — the chunked form of softmax-minus-onehot."""
    eps = cfg.label_smoothing
    p = jnp.exp(logits - lse_c[:, None])
    col = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    d = p * coef_c[:, None]
    d = d - jnp.where(col == lab_c[:, None],
                      (1.0 - eps) * ctv_c[:, None], 0.0)
    if eps:
        d = d - (eps / v_total) * ctv_c[:, None]
    return d


def _mp_fix_grads(cfg: _CECfg, axis, dx, dw, db):
    """Cotangent bookkeeping under bound mp (shard_map with replication
    checking off, the repo-wide shard_map_compat convention): the cotangent
    of the replicated per-token loss arrives pre-divided by the axis size,
    and the boundary transpose psums only REPLICATED inputs. So:
      * has_w: x is replicated — psum the partial dx (÷world × boundary psum
        nets out to the true total); w (and bias) are vocab-shard inputs whose
        cotangents pass through untouched — scale them back by world.
      * logits-level (no w): the logits input is itself vocab-sharded — its
        local d-logits tile is already complete, only the ÷world undone.
    Parity-gated by the mp cases of tests/test_fused_cross_entropy.py."""
    if axis is None:
        return dx, dw, db
    world = jax.lax.psum(1, axis)
    if not cfg.has_w:
        return dx * world, dw, db
    dx = jax.lax.psum(dx, axis)
    dw = dw * world
    if db is not None:
        db = db * world
    return dx, dw, db


def _bwd_tokens(cfg: _CECfg, x, w, b, labels, lse, ct):
    n = x.shape[0]
    vloc = w.shape[1] if cfg.has_w else x.shape[-1]
    lab_loc, axis, v_total = _local_labels(cfg, labels, vloc)
    ctv, coef_p = _bwd_coefs(cfg, labels, lse, ct)
    c = cfg.chunk_tokens
    xp, lp, nc = _pad_tokens(x, lab_loc, c)
    aux = jnp.stack([jnp.pad(lse, (0, nc * c - n)),
                     jnp.pad(ctv, (0, nc * c - n)),
                     jnp.pad(coef_p, (0, nc * c - n))], axis=-1)
    xc = xp.reshape((nc, c) + xp.shape[1:])
    lc = lp.reshape(nc, c)
    ac = aux.reshape(nc, c, 3)
    wf = w.astype(jnp.float32) if cfg.has_w else None

    def step(carry, args):
        xi, li, ai = args
        logits = (_project(xi, w, b, cfg.fp8) if cfg.has_w
                  else xi.astype(jnp.float32))
        d = _chunk_dlogits(cfg, logits, li, ai[:, 0], ai[:, 1], ai[:, 2],
                           v_total)
        if not cfg.has_w:
            return carry, d
        dw_acc, db_acc = carry
        if cfg.fp8:
            # gradient tile in e5m2, x/w in e4m3; the dw accumulator stays
            # fp32 (only the matmuls change precision)
            dxi = _fp8_mm(d, wf.T, a_e5m2=True)
            dw_acc = dw_acc + _fp8_mm(d.T, xi.astype(jnp.float32),
                                      a_e5m2=True).T
        else:
            dxi = jnp.dot(d, wf.T, preferred_element_type=jnp.float32)
            dw_acc = dw_acc + jnp.dot(xi.astype(jnp.float32).T, d,
                                      preferred_element_type=jnp.float32)
        if db_acc is not None:
            db_acc = db_acc + jnp.sum(d, axis=0)
        return (dw_acc, db_acc), dxi

    init = ((jnp.zeros(w.shape, jnp.float32),
             jnp.zeros((vloc,), jnp.float32) if cfg.has_bias else None)
            if cfg.has_w else None)
    carry, dxs = jax.lax.scan(step, init, (xc, lc, ac))
    dx = dxs.reshape((nc * c,) + dxs.shape[2:])[:n]
    dx, dw_acc, db_acc = _mp_fix_grads(
        cfg, axis, dx, *(carry if cfg.has_w else (None, None)))
    dx = dx.astype(x.dtype)
    if not cfg.has_w:
        return dx, None, None
    return dx, dw_acc.astype(w.dtype), (
        db_acc.astype(b.dtype) if cfg.has_bias else None)


def _bwd_vocab(cfg: _CECfg, x, w, b, labels, lse, ct):
    n, vloc = x.shape[0], w.shape[1]
    lab_loc, axis, v_total = _local_labels(cfg, labels, vloc)
    ctv, coef_p = _bwd_coefs(cfg, labels, lse, ct)
    cv = cfg.chunk_vocab
    wp, bp, nc = _pad_vocab(w, b, vloc, cv)
    wc = jnp.moveaxis(wp.reshape(wp.shape[0], nc, cv), 1, 0)
    bc = bp.reshape(nc, cv) if b is not None else None
    xf = x.astype(jnp.float32)

    def step(dx_acc, args):
        j, wi = args[0], args[1]
        if cfg.fp8:
            logits = _fp8_mm(xf, wi)
        else:
            logits = jnp.dot(xf, wi.astype(jnp.float32),
                             preferred_element_type=jnp.float32)
        if bc is not None:
            logits = logits + args[2].astype(jnp.float32)
        # labels shifted into this chunk's [0, cv) frame, then padding
        # columns (>= vloc) zeroed
        d = _chunk_dlogits(cfg, logits, lab_loc - j * cv, lse, ctv, coef_p,
                           v_total)
        col = j * cv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        d = jnp.where(col < vloc, d, 0.0)
        if cfg.fp8:
            dx_acc = dx_acc + _fp8_mm(d, wi.astype(jnp.float32).T,
                                      a_e5m2=True)
            dwi = _fp8_mm(d.T, xf, a_e5m2=True)  # [cv, H]
        else:
            dx_acc = dx_acc + jnp.dot(d, wi.astype(jnp.float32).T,
                                      preferred_element_type=jnp.float32)
            dwi = jnp.dot(d.T, xf, preferred_element_type=jnp.float32)
        return dx_acc, (dwi, jnp.sum(d, axis=0))

    xs = (jnp.arange(nc, dtype=jnp.int32), wc) + ((bc,) if bc is not None else ())
    dx, (dwis, dbis) = jax.lax.scan(step, jnp.zeros(x.shape, jnp.float32), xs)
    dw = jnp.transpose(dwis, (2, 0, 1)).reshape(w.shape[0], nc * cv)[:, :vloc]
    db = dbis.reshape(nc * cv)[:vloc] if cfg.has_bias else None
    dx, dw, db = _mp_fix_grads(cfg, axis, dx, dw, db)
    return dx.astype(x.dtype), dw.astype(w.dtype), (
        db.astype(b.dtype) if cfg.has_bias else None)


# ---------------------------------------------------------------------------
# custom_vjp assembly (cached per static config)
# ---------------------------------------------------------------------------


def _label_zero(labels):
    return np.zeros(labels.shape, jax.dtypes.float0)


@functools.lru_cache(maxsize=None)
def _build_linear_ce(cfg: _CECfg):
    if cfg.has_bias:
        @jax.custom_vjp
        def f(x, w, b, labels):
            return _fwd_impl(cfg, x, w, b, labels)[0]

        def fwd(x, w, b, labels):
            loss, lse = _fwd_impl(cfg, x, w, b, labels)
            return loss, (x, w, b, labels, lse)

        def bwd(res, ct):
            x, w, b, labels, lse = res
            bwd_fn = _bwd_vocab if cfg.variant == "vocab" else _bwd_tokens
            dx, dw, db = bwd_fn(cfg, x, w, b, labels, lse, ct)
            return dx, dw, db, _label_zero(labels)
    else:
        @jax.custom_vjp
        def f(x, w, labels):
            return _fwd_impl(cfg, x, w, None, labels)[0]

        def fwd(x, w, labels):
            loss, lse = _fwd_impl(cfg, x, w, None, labels)
            return loss, (x, w, labels, lse)

        def bwd(res, ct):
            x, w, labels, lse = res
            bwd_fn = _bwd_vocab if cfg.variant == "vocab" else _bwd_tokens
            dx, dw, _ = bwd_fn(cfg, x, w, None, labels, lse, ct)
            return dx, dw, _label_zero(labels)

    f.defvjp(fwd, bwd)
    return f


@functools.lru_cache(maxsize=None)
def _build_softmax_ce(cfg: _CECfg):
    @jax.custom_vjp
    def f(logits, labels):
        return _fwd_impl(cfg, logits, None, None, labels)[0]

    def fwd(logits, labels):
        loss, lse = _fwd_impl(cfg, logits, None, None, labels)
        return loss, (logits, labels, lse)

    def bwd(res, ct):
        logits, labels, lse = res
        dx, _, _ = _bwd_tokens(cfg, logits, None, None, labels, lse, ct)
        return dx, _label_zero(labels)

    f.defvjp(fwd, bwd)
    return f


def _resolve_cfg(n, vloc, ignore_index, label_smoothing, z_loss, chunk_tokens,
                 chunk_vocab, variant, mp_axis, has_w, has_bias):
    from paddle_tpu.core.flags import flag

    if chunk_tokens > 0 or chunk_vocab > 0:
        # caller-supplied chunking wins outright (resolve_chunks fills a
        # partially-specified pair from the heuristic)
        ct, cv = resolve_chunks(n, vloc, chunk_tokens, chunk_vocab)
    else:
        from paddle_tpu.tuning.blocks import resolve_blocks

        res = resolve_blocks(
            "fused_ce", {"n_tokens": int(n), "vocab": int(vloc)},
            default=lambda g: resolve_chunks(n, vloc))
        ct = min(int(res.values["chunk_tokens"]), max(int(n), 1))
        cv = min(int(res.values["chunk_vocab"]), max(int(vloc), 1))
    # fp8_policy='matmuls+head': the projection matmuls quantize (stats stay
    # fp32). The Pallas stats kernel is bf16/fp32-only, so fp8 resolves to
    # the token-chunked scan variant instead.
    from paddle_tpu.amp.fp8 import head_fp8_enabled

    fp8 = bool(has_w and head_fp8_enabled())
    if variant in (None, "", "auto"):
        variant = flag("fused_ce_variant")
    if variant in (None, "", "auto"):
        variant = ("pallas" if (has_w and not has_bias and _on_tpu()
                                and not fp8)
                   else "tokens")
    if fp8 and variant == "pallas":
        variant = "tokens"
    if mp_axis == "auto":
        from paddle_tpu.distributed.collective import _bound_axes
        from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import MP_AXIS

        mp_axis = MP_AXIS if _bound_axes((MP_AXIS,)) else None
    return _CECfg(int(ignore_index), float(label_smoothing), float(z_loss),
                  ct, cv, variant, mp_axis, has_w, has_bias, fp8)


def fused_linear_cross_entropy_loss(x, w, labels, bias=None, *,
                                    ignore_index=-100, label_smoothing=0.0,
                                    z_loss=0.0, chunk_tokens=0, chunk_vocab=0,
                                    variant="auto", mp_axis="auto"):
    """Per-token fp32 loss of ``CE(x @ w + bias, labels)`` without the
    [tokens, vocab] logits. x: [N, H]; w: [H, V] (the local shard under bound
    mp); labels: [N] int. Ignored tokens contribute 0."""
    _check_labels(labels)
    cfg = _resolve_cfg(x.shape[0], w.shape[1], ignore_index, label_smoothing,
                       z_loss, chunk_tokens, chunk_vocab, variant, mp_axis,
                       True, bias is not None)
    if cfg.variant == "pallas" and bias is not None:
        cfg = cfg._replace(variant="tokens")
    fn = _build_linear_ce(cfg)
    if bias is not None:
        return fn(x, w, bias, labels)
    return fn(x, w, labels)


def softmax_cross_entropy_loss(logits, labels, *, ignore_index=-100,
                               label_smoothing=0.0, z_loss=0.0,
                               chunk_tokens=0, mp_axis="auto"):
    """Per-token fp32 softmax-CE on pre-computed (possibly vocab-sharded)
    logits [N, V_local], always token-chunked (the only variant that makes
    sense without the projection) so neither the log-softmax nor the
    backward softmax is ever materialized at [N, V]."""
    _check_labels(labels)
    cfg = _resolve_cfg(logits.shape[0], logits.shape[-1], ignore_index,
                       label_smoothing, z_loss, chunk_tokens, 0, "tokens",
                       mp_axis, False, False)
    return _build_softmax_ce(cfg)(logits, labels)
