"""Pallas grouped (ragged) matmul over expert buckets — the dropless-MoE
compute primitive.

Reference analog: the reference MoE stack runs each expert's FFN over a
fixed-capacity `[E, C, d]` bucket tensor (incubate/distributed/models/moe),
padding to capacity and dropping overflow. Here the buckets are RAGGED: rows
arrive grouped by expert id (`gids`, non-decreasing within the dispatch
layout the MoE dispatcher emits) and each expert's matmul runs over exactly
its rows — O(actual tokens), not O(E*C).

Kernel design (the PR-5/PR-9 ragged-block pattern, expert buckets as one
more segment vocabulary):

  * forward — grid (row_blocks, G). The output tile [bm, h] for row block i
    accumulates over the trailing (sequential on TPU) group dim; a group g
    is SKIPPED for row block i unless g intersects the block's group-id
    range — the SAME `_seg_blocks_can_touch` predicate the flash/paged
    attention kernels use for packed-segment block skipping. With the
    dispatcher's block-aligned layout each row block matches exactly one
    group, so the kernel visits (row_blocks) of (row_blocks*G) tiles.
  * dx — the forward kernel over `w` transposed (same skip structure).
  * dw — grid (G, row_blocks): dw[g] accumulates masked x_blk^T @ dy_blk
    across the trailing row-block dim under the same predicate.
  * `grouped_matmul_visit_counts` runs the predicate as its own kernel so
    the bench counter provably counts what the compute kernels execute
    (mirrors `segment_block_visit_counts`).

Accumulation is fp32 (the returned array is fp32; callers cast), so bf16
inputs meet the dense-reference parity bounds.

Backends: `pallas` (TPU, or interpret mode under `force_interpret()` so
tier-1 CPU tests exercise the exact kernel code), and an `xla` fallback —
a block-gather batched matmul (`w[blk_gid]` per row block) that is exact
for BLOCK-ALIGNED layouts (every bm-row block holds rows of one group,
which is what the dispatcher guarantees; rows disagreeing with their
block's leading group id contribute zero). `auto` picks pallas on TPU /
forced-interpret and xla elsewhere.

Rows with `gids == num_groups` are padding/overflow ("trash") rows: no
kernel tile ever matches them, so their output rows stay zero.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas._compat import x64_off as _x64_off
from paddle_tpu.ops.pallas.flash_attention import (
    _interpret_mode, _seg_blocks_can_touch, interpret_forced,
)

__all__ = ["grouped_matmul", "grouped_matmul_visit_counts",
           "expected_visit_counts", "pick_block_rows"]


def _heuristic_block_rows(n_rows: int, num_groups: int) -> int:
    for bm in (128, 32, 8):
        if n_rows >= bm * max(num_groups, 1):
            return bm
    return 8


def pick_block_rows(n_rows: int, num_groups: int) -> int:
    """Rows per grid block, through the shared tuning resolver:
    FLAGS_moe_block_rows override > tuned entry > heuristic (128 —
    MXU-friendly — when buckets are large enough that per-group alignment
    padding stays small, stepping down for tiny problems)."""
    from paddle_tpu.tuning.blocks import resolve_blocks

    res = resolve_blocks(
        "grouped_matmul", {"n_rows": n_rows, "num_groups": num_groups},
        default=lambda g: (_heuristic_block_rows(n_rows, num_groups),))
    return res.values["block_rows"]


def _resolve_backend(backend: str | None) -> str:
    from paddle_tpu.core.flags import flag

    backend = backend or flag("moe_gmm_backend")
    if backend == "auto":
        if interpret_forced():
            return "pallas"
        on_tpu = jax.default_backend() == "tpu"
        return "pallas" if on_tpu else "xla"
    if backend not in ("pallas", "xla"):
        raise ValueError(f"moe_gmm_backend={backend!r}: auto|pallas|xla")
    return backend


# ---------------------------------------------------------------------------
# pallas kernels
# ---------------------------------------------------------------------------

def _gmm_fwd_kernel(gid_ref, x_ref, w_ref, o_ref):
    g = pl.program_id(1)

    @pl.when(g == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    gid = gid_ref[0]                                     # [bm] int32
    needed = _seg_blocks_can_touch(jnp.min(gid), jnp.max(gid), g, g)

    @pl.when(needed)
    def _compute():
        x = x_ref[...].astype(jnp.float32)               # [bm, d]
        w = w_ref[0].astype(jnp.float32)                 # [d, h]
        mask = (gid == g).astype(jnp.float32)[:, None]
        o_ref[...] += jax.lax.dot(x * mask, w,
                                  preferred_element_type=jnp.float32)


def _gmm_dw_kernel(gid_ref, x_ref, dy_ref, dw_ref):
    g = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    gid = gid_ref[0]
    needed = _seg_blocks_can_touch(jnp.min(gid), jnp.max(gid), g, g)

    @pl.when(needed)
    def _compute():
        x = x_ref[...].astype(jnp.float32)               # [bm, d]
        dy = dy_ref[...].astype(jnp.float32)             # [bm, h]
        mask = (gid == g).astype(jnp.float32)[:, None]
        dw_ref[0] += jax.lax.dot((x * mask).T, dy,
                                 preferred_element_type=jnp.float32)


def _gmm_fwd_pallas(x, w, gids, block_rows, interpret):
    m, d = x.shape
    num_groups, _, h = w.shape
    gid2 = gids.reshape(1, m)
    with _x64_off():
        return pl.pallas_call(
            _gmm_fwd_kernel,
            grid=(m // block_rows, num_groups),
            in_specs=[
                pl.BlockSpec((1, block_rows), lambda i, g: (0, i)),
                pl.BlockSpec((block_rows, d), lambda i, g: (i, 0)),
                pl.BlockSpec((1, d, h), lambda i, g: (g, 0, 0)),
            ],
            out_specs=pl.BlockSpec((block_rows, h), lambda i, g: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((m, h), jnp.float32),
            interpret=interpret,
        )(gid2, x, w)


def _gmm_dw_pallas(x, dy, gids, num_groups, block_rows, interpret):
    m, d = x.shape
    h = dy.shape[1]
    gid2 = gids.reshape(1, m)
    with _x64_off():
        return pl.pallas_call(
            _gmm_dw_kernel,
            grid=(num_groups, m // block_rows),
            in_specs=[
                pl.BlockSpec((1, block_rows), lambda g, i: (0, i)),
                pl.BlockSpec((block_rows, d), lambda g, i: (i, 0)),
                pl.BlockSpec((block_rows, h), lambda g, i: (i, 0)),
            ],
            out_specs=pl.BlockSpec((1, d, h), lambda g, i: (g, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((num_groups, d, h), jnp.float32),
            interpret=interpret,
        )(gid2, x, dy)


# ---------------------------------------------------------------------------
# public custom-vjp entry (pallas kernels, or the xla block-gather fallback —
# a batched matmul over w[blk_gid], exact for block-aligned layouts)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _gmm(x, w, gids, num_groups, block_rows, backend, interpret):
    return _gmm_forward(x, w, gids, num_groups, block_rows, backend,
                        interpret)


def _gmm_forward(x, w, gids, num_groups, block_rows, backend, interpret):
    if backend == "pallas":
        return _gmm_fwd_pallas(x, w, gids, block_rows, interpret)
    m, d = x.shape
    bm = block_rows
    xb = x.reshape(m // bm, bm, d)
    gb = gids.reshape(m // bm, bm)
    blk_g = gb[:, 0]
    wb = jnp.take(w, jnp.clip(blk_g, 0, num_groups - 1), axis=0)
    mask = jnp.logical_and(gb == blk_g[:, None], gb < num_groups)
    xm = xb.astype(jnp.float32) * mask.astype(jnp.float32)[..., None]
    y = jnp.einsum("bmd,bdh->bmh", xm, wb.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    return y.reshape(m, w.shape[-1])


def _gmm_backward_dw(x, dy, gids, num_groups, block_rows, backend, interpret):
    if backend == "pallas":
        return _gmm_dw_pallas(x, dy, gids, num_groups, block_rows, interpret)
    m, d = x.shape
    h = dy.shape[1]
    bm = block_rows
    xb = x.reshape(m // bm, bm, d)
    gb = gids.reshape(m // bm, bm)
    blk_g = gb[:, 0]
    mask = jnp.logical_and(gb == blk_g[:, None], gb < num_groups)
    xm = xb.astype(jnp.float32) * mask.astype(jnp.float32)[..., None]
    per_block = jnp.einsum("bmd,bmh->bdh", xm,
                           dy.reshape(m // bm, bm, h).astype(jnp.float32),
                           preferred_element_type=jnp.float32)
    return jnp.zeros((num_groups, d, h), jnp.float32).at[
        jnp.clip(blk_g, 0, num_groups - 1)].add(
        per_block * (blk_g < num_groups).astype(jnp.float32)[:, None, None])


def _gmm_vjp_fwd(x, w, gids, num_groups, block_rows, backend, interpret):
    y = _gmm_forward(x, w, gids, num_groups, block_rows, backend, interpret)
    return y, (x, w, gids)


def _gmm_vjp_bwd(num_groups, block_rows, backend, interpret, res, dy):
    x, w, gids = res
    # dx: the SAME grouped structure over w transposed; dw: per-group
    # accumulation under the same block-skip predicate
    dx = _gmm_forward(dy, jnp.swapaxes(w, 1, 2).astype(jnp.float32), gids,
                      num_groups, block_rows, backend, interpret)
    dw = _gmm_backward_dw(x, dy, gids, num_groups, block_rows, backend,
                          interpret)
    dgids = np.zeros(gids.shape, jax.dtypes.float0)
    return dx.astype(x.dtype), dw.astype(w.dtype), dgids


_gmm.defvjp(_gmm_vjp_fwd, _gmm_vjp_bwd)


def grouped_matmul(x, w, gids, *, block_rows: int | None = None,
                   backend: str | None = None):
    """y[i] = x[i] @ w[gids[i]] over ragged, group-contiguous rows.

    x: [M, d]; w: [G, d, h]; gids: [M] int32 in [0, G] — rows with
    `gids == G` are padding and yield zero rows. M must be a multiple of
    `block_rows`. Returns fp32 [M, h] (fp32 accumulation regardless of
    input dtype). Differentiable in x and w (custom-vjp; dx/dw run the
    grouped kernels, never a dense [M, G] mask).

    Layout contract: rows grouped by id with each block_rows-row block
    belonging to one group (what the MoE dispatcher emits). The pallas
    backend additionally masks within blocks, so it is exact for any
    grouped layout; the xla fallback zeroes rows that disagree with their
    block's leading id.
    """
    m, d = x.shape
    num_groups = w.shape[0]
    if gids.shape != (m,):
        raise ValueError(f"gids shape {gids.shape} != ({m},)")
    bm = block_rows or pick_block_rows(m, num_groups)
    if m % bm:
        # Surface the bad launch config here with its provenance — without
        # this check it dies inside Pallas grid setup with an opaque shape
        # error (the flash-attention block-validation idiom from PR-5).
        if block_rows is not None:
            src = f"block_rows={block_rows} (caller-supplied)"
        else:
            from paddle_tpu.tuning.blocks import last_resolution

            res = last_resolution("grouped_matmul")
            prov = res.provenance if res is not None else "default"
            detail = {"flag": "FLAGS_moe_block_rows override",
                      "tuned": "tuning-cache entry",
                      "default": "auto-picked"}.get(prov, prov)
            src = f"block_rows={bm} ({detail})"
        raise ValueError(
            f"grouped_matmul: rows {m} not a multiple of {src}; pad the "
            f"row count to a multiple of the block, or set "
            f"FLAGS_moe_block_rows to a divisor of {m}")
    backend = _resolve_backend(backend)
    interpret = _interpret_mode() if backend == "pallas" else False
    return _gmm(x, w, gids.astype(jnp.int32), num_groups, bm, backend,
                interpret)


# ---------------------------------------------------------------------------
# visit-count kernel (the bench counter)
# ---------------------------------------------------------------------------

def _visit_kernel(gid_ref, o_ref, *, num_groups: int):
    gid = gid_ref[0]
    gmin = jnp.min(gid)
    gmax = jnp.max(gid)
    gs = jax.lax.broadcasted_iota(jnp.int32, (1, num_groups), 1)
    visited = _seg_blocks_can_touch(gmin, gmax, gs, gs)
    o_ref[...] = jnp.sum(visited.astype(jnp.float32)).reshape(1, 1)


def grouped_matmul_visit_counts(gids, num_groups: int, block_rows: int,
                                interpret: bool | None = None):
    """Per-row-block count of groups the grouped-matmul kernels VISIT,
    computed by running the forward kernel's exact `_seg_blocks_can_touch`
    predicate as its own Pallas kernel (mirror of
    `segment_block_visit_counts`). int32 [M // block_rows];
    sum()/ (blocks * G) is the visited fraction the MOE bench arm reports.
    Padding rows (`gids == num_groups`) never match any group."""
    gids = jnp.asarray(gids, jnp.int32)
    (m,) = gids.shape
    if interpret is None:
        interpret = _interpret_mode()
    kernel = functools.partial(_visit_kernel, num_groups=num_groups)
    with _x64_off():
        cnt = pl.pallas_call(
            kernel,
            grid=(m // block_rows,),
            in_specs=[pl.BlockSpec((1, block_rows), lambda i: (0, i))],
            out_specs=pl.BlockSpec((1, 1), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((m // block_rows, 1), jnp.float32),
            interpret=interpret,
        )(gids.reshape(1, m))
    return cnt[:, 0].astype(jnp.int32)


def expected_visit_counts(gids, num_groups: int, block_rows: int):
    """The same predicate evaluated in plain numpy — the cross-check the
    bench asserts against the kernel counter."""
    g = np.asarray(gids, np.int32).reshape(-1, block_rows)
    gmin = g.min(axis=1)[:, None]
    gmax = g.max(axis=1)[:, None]
    gs = np.arange(num_groups, dtype=np.int32)[None, :]
    return np.logical_and(gs <= gmax, gs >= gmin).sum(axis=1).astype(np.int32)
