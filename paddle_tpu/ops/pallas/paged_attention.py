"""Pallas paged/ragged decode-attention for TPU serving (PagedAttention).

Reference analog: the vLLM PagedAttention idea mapped onto the machinery
this repo already has — a per-sequence page table is just another
block-validity mask for the segment block-skip predicate the PR-5 flash
kernels use (`_seg_blocks_can_touch` in ops/pallas/flash_attention.py).

Layout (vLLM-style, block-granular KV cache):

  * K/V page pools: ``[num_kv_heads, num_pages, page_size, head_dim]`` —
    every page holds `page_size` consecutive tokens of ONE request.
  * page table: ``[batch, pages_per_seq]`` int32 — row b lists the pool
    pages that back request b's context, in order; unused trailing slots
    point at the reserved NULL page 0 (never handed to a request by the
    allocator, so a dead slot's DMA is harmless and compute is skipped).
  * context_lens: ``[batch]`` int32 — valid tokens per request (0 marks an
    inactive row of the fixed-size decode batch; its output is zeros).

TPU-native design: ``PrefetchScalarGridSpec`` prefetches (context_lens,
page_table) into SMEM so the K/V BlockSpec *index maps* gather pages —
grid (batch, kv_heads, pages_per_seq), one page per trailing grid step,
online-softmax state carried in VMEM scratch across the (sequential on
TPU) page dimension. GQA is native: the q block for a kv head is its
whole query-head group, K/V are never repeated.

Ragged cost: a page contributes only when the query's valid key range
[0, len-1] intersects the page's position range — literally
``_seg_blocks_can_touch(0, len-1, p*ps, p*ps+ps-1)``, THE predicate the
flash kernels share — so decode compute is O(sum_b ceil(len_b / ps))
pages, not O(batch * pages_per_seq). `page_visit_counts` runs that same
predicate as a standalone kernel = the bench utilization counter.

Off-TPU the public entry point routes to a jnp gather reference
(`paged_attention_reference`, identical math) the way
F.scaled_dot_product_attention falls back to XLA; `force_interpret()`
pins the exact Pallas kernel in interpret mode instead (the conftest
`paged_interpret` fixture), so tier-1 CPU runs the same kernel code the
TPU compiles through Mosaic.
"""
from __future__ import annotations

import functools
import math
import threading
from contextlib import contextmanager

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas._compat import x64_off as _x64_off
from paddle_tpu.ops.pallas.flash_attention import (_on_tpu,
                                                   _seg_blocks_can_touch)

try:  # pallas TPU backend may be absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

__all__ = ["paged_attention", "paged_decode_attention",
           "paged_attention_reference", "page_visit_counts",
           "force_interpret", "interpret_forced"]

_NEG_INF = -1e30


class _InterpretTLS(threading.local):
    def __init__(self):
        self.force = False


_interp_tls = _InterpretTLS()


@contextmanager
def force_interpret():
    """Run the paged kernels in interpret mode regardless of platform — the
    hardware-free path tier-1 uses to exercise the exact TPU kernel
    (mirrors flash_attention.force_interpret)."""
    prev = _interp_tls.force
    _interp_tls.force = True
    try:
        yield
    finally:
        _interp_tls.force = prev


def interpret_forced() -> bool:
    return _interp_tls.force


def _interpret_mode() -> bool:
    return _interp_tls.force or not _on_tpu()


# ---------------------------------------------------------------------------
# decode kernel
# ---------------------------------------------------------------------------

def _decode_kernel(lens_ref, pt_ref, q_ref, k_ref, v_ref, *rest,
                   page_size: int, scale: float, pages_per_seq: int,
                   q_len: int, group: int, quantized: bool = False):
    # quantized pools ride two extra per-page scale blocks (the in-kernel
    # dequant of PR-16: bf16 K/V never materialize in HBM); the trailing
    # refs are always (o, m, l, acc)
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    length = lens_ref[b]
    first = p * page_size
    # THE shared block-skip predicate: the LAST query's valid key range is
    # [0, len+q_len-2] (verify query i sits at absolute position
    # len-1+i and may attend keys <= its own position; q_len==1 is plain
    # decode with range [0, len-1]), page p covers positions
    # [first, first+ps-1]; a page whose range can't intersect contributes
    # nothing, and len==0 rows skip ALL pages
    needed = _seg_blocks_can_touch(0, length + (q_len - 1) - 1, first,
                                   first + page_size - 1) & (length > 0)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale      # [T*G, D]
        k = k_ref[0, 0].astype(jnp.float32)               # [PS, D]
        v = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            # fused dequant: per-slot-per-head absmax scales stream in
            # alongside the page; the bf16 values exist only in VMEM
            k = k * ks_ref[0, 0][:, None]
            v = v * vs_ref[0, 0][:, None]
        g = q.shape[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [T*G, PS]
        k_pos = first + jax.lax.broadcasted_iota(jnp.int32, (g, page_size), 1)
        # per-query causal limit: query row r belongs to frame r // group
        # at absolute position length-1 + r//group -> keys < length + r//group
        # (lax.div with an explicit i32 divisor: a Python-int `//` would
        # promote to i64 under an x64-enabled outer trace in interpret mode)
        q_frame = jax.lax.div(
            jax.lax.broadcasted_iota(jnp.int32, (g, page_size), 0),
            jnp.int32(group))
        s = jnp.where(k_pos < length + q_frame, s, jnp.float32(_NEG_INF))
        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        pexp = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(pexp, axis=-1,
                                                  keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            pexp, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(p == pages_per_seq - 1)
    def _finish():
        # inactive rows (len 0) never accumulated: l==0 -> output zeros
        o_ref[0, 0] = (acc_ref[...] /
                       jnp.maximum(l_ref[...],
                                   jnp.float32(1e-30))).astype(o_ref.dtype)


def _check_shapes(q, k_pages, v_pages, page_table, context_lens):
    if q.ndim == 4:                     # [B, T, Hq, D] verify frame
        b, _, hq, d = q.shape
    else:
        b, hq, d = q.shape
    hkv, _, ps, dk = k_pages.shape
    if v_pages.shape != k_pages.shape:
        raise ValueError(f"k_pages {k_pages.shape} != v_pages "
                         f"{v_pages.shape}")
    if dk != d:
        raise ValueError(f"head_dim mismatch: q {d} vs pages {dk}")
    if hkv == 0 or hq % hkv != 0:
        raise ValueError(
            f"q heads must be a multiple of kv heads, got {hq} and {hkv}")
    if page_table.shape[0] != b or page_table.ndim != 2:
        raise ValueError(f"page_table must be [batch={b}, pages_per_seq], "
                         f"got {page_table.shape}")
    if context_lens.shape != (b,):
        raise ValueError(f"context_lens must be [batch={b}], "
                         f"got {context_lens.shape}")
    return b, hq, hkv, ps, d


def paged_decode_attention(q, k_pages, v_pages, page_table, context_lens,
                           scale: float | None = None,
                           interpret: bool | None = None,
                           k_scales=None, v_scales=None):
    """Attention over the paged KV cache (the Pallas kernel). q is either
    ``[B, Hq, D]`` (one query token per sequence — plain decode) or
    ``[B, T, Hq, D]`` (a speculative VERIFY frame: query i of row b sits at
    absolute position ``context_lens[b] - 1 + i`` and attends causally up
    to its own position, so ONE pass scores a whole draft window).
    k_pages/v_pages: [Hkv, P, page_size, D]; page_table:
    [B, pages_per_seq] int32; context_lens: [B] int32 counts committed
    context INCLUDING the frame's first (rewrite) token. Returns q's shape.

    Quantized pools: when ``k_scales``/``v_scales`` (``[Hkv, P, page_size]``
    float32 per-slot-per-head absmax scales) are given, k/v pages hold
    int8/fp8 codes and the kernel dequantizes INSIDE the grid step — the
    scale block streams alongside its page via the same index-map gather,
    so bf16 values exist only in VMEM, never in HBM.
    """
    b, hq, hkv, ps, d = _check_shapes(q, k_pages, v_pages, page_table,
                                      context_lens)
    quantized = k_scales is not None
    if quantized and v_scales is None:
        raise ValueError("k_scales given without v_scales")
    if quantized:
        want = (hkv, k_pages.shape[1], ps)
        if tuple(k_scales.shape) != want or tuple(v_scales.shape) != want:
            raise ValueError(
                f"k/v scales must be [Hkv, P, page_size]={want}, got "
                f"{tuple(k_scales.shape)} and {tuple(v_scales.shape)}")
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    t = q.shape[1]
    group = hq // hkv
    tg = t * group
    pages_per_seq = page_table.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = _interpret_mode()
    if not interpret and not _HAS_PLTPU:  # pragma: no cover
        raise RuntimeError("pallas TPU backend unavailable; use "
                           "paged_attention_reference or force_interpret()")
    # [B, T, Hkv, G, D] -> [B, Hkv, T*G, D]: the kernel's q block carries
    # the whole verify window, frame index recovered as row // group
    qg = (q.reshape(b, t, hkv, group, d).transpose(0, 2, 1, 3, 4)
          .reshape(b, hkv, tg, d))
    kernel = functools.partial(_decode_kernel, page_size=ps, scale=scale,
                               pages_per_seq=pages_per_seq, q_len=t,
                               group=group, quantized=quantized)
    in_specs = [
        pl.BlockSpec((1, 1, tg, d),
                     lambda bb, h, p, lens, pt: (bb, h, 0, 0)),
        # the page gather IS the index map: scalar-prefetched page-table
        # entries pick which pool page streams into VMEM this grid step
        pl.BlockSpec((1, 1, ps, d),
                     lambda bb, h, p, lens, pt: (h, pt[bb, p], 0, 0)),
        pl.BlockSpec((1, 1, ps, d),
                     lambda bb, h, p, lens, pt: (h, pt[bb, p], 0, 0)),
    ]
    operands = [qg, k_pages, v_pages]
    if quantized:
        # each page's scale block rides the same gather as the page itself
        in_specs += [
            pl.BlockSpec((1, 1, ps),
                         lambda bb, h, p, lens, pt: (h, pt[bb, p], 0)),
            pl.BlockSpec((1, 1, ps),
                         lambda bb, h, p, lens, pt: (h, pt[bb, p], 0)),
        ]
        operands += [jnp.asarray(k_scales, jnp.float32),
                     jnp.asarray(v_scales, jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, pages_per_seq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, tg, d),
                               lambda bb, h, p, lens, pt: (bb, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tg, 1), jnp.float32),
            pltpu.VMEM((tg, 1), jnp.float32),
            pltpu.VMEM((tg, d), jnp.float32),
        ],
    )
    with _x64_off():
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, hkv, tg, d), q.dtype),
            interpret=interpret,
        )(jnp.asarray(context_lens, jnp.int32),
          jnp.asarray(page_table, jnp.int32), *operands)
    out = (out.reshape(b, hkv, t, group, d).transpose(0, 2, 1, 3, 4)
           .reshape(b, t, hq, d))
    return out[:, 0] if squeeze else out


# ---------------------------------------------------------------------------
# XLA reference (identical math; the off-TPU serving fast path)
# ---------------------------------------------------------------------------

def paged_attention_reference(q, k_pages, v_pages, page_table, context_lens,
                              scale: float | None = None,
                              k_scales=None, v_scales=None):
    """jnp gather + masked-softmax reference of `paged_decode_attention` —
    the XLA fallback the serving engine uses off-TPU (fast under jit on
    CPU, where interpret-mode Pallas would run the grid in Python).
    Accepts the same [B, Hq, D] decode and [B, T, Hq, D] verify-frame
    query layouts with identical per-query causal semantics, and the same
    optional ``k_scales``/``v_scales`` ``[Hkv, P, page_size]`` dequant
    contract as the kernel (scales applied after the f32 cast, so CPU
    tier-1 runs the exact quantized semantics)."""
    b, hq, hkv, ps, d = _check_shapes(q, k_pages, v_pages, page_table,
                                      context_lens)
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    t = q.shape[1]
    group = hq // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    s_max = page_table.shape[1] * ps
    pt = jnp.asarray(page_table, jnp.int32)
    lens = jnp.asarray(context_lens, jnp.int32)
    # [Hkv, B, Pmax, PS, D] -> [B, Hkv, S, D]
    k = jnp.moveaxis(k_pages[:, pt], 1, 0).reshape(b, hkv, s_max, d)
    v = jnp.moveaxis(v_pages[:, pt], 1, 0).reshape(b, hkv, s_max, d)
    if k_scales is not None:
        ks = jnp.moveaxis(jnp.asarray(k_scales, jnp.float32)[:, pt],
                          1, 0).reshape(b, hkv, s_max)
        vs = jnp.moveaxis(jnp.asarray(v_scales, jnp.float32)[:, pt],
                          1, 0).reshape(b, hkv, s_max)
        k = k.astype(jnp.float32) * ks[..., None]
        v = v.astype(jnp.float32) * vs[..., None]
    qg = q.reshape(b, t, hkv, group, d).astype(jnp.float32) * scale
    s = jnp.einsum("bthgd,bhsd->bthgs", qg, k.astype(jnp.float32))
    pos = jnp.arange(s_max, dtype=jnp.int32)
    # per-query causal limit: frame i attends keys < lens + i (its own
    # absolute position lens-1+i included)
    limit = lens[:, None] + jnp.arange(t, dtype=jnp.int32)[None]   # [B, T]
    s = jnp.where(pos[None, None, None, None, :]
                  < limit[:, :, None, None, None], s, _NEG_INF)
    # inactive rows (len 0): every position masked; renormalize safely to 0
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    active = (lens > 0)[:, None, None, None, None]
    out = jnp.einsum("bthgs,bhsd->bthgd", p / jnp.maximum(denom, 1e-30),
                     v.astype(jnp.float32))
    out = jnp.where(active, out, 0.0)
    out = out.reshape(b, t, hq, d).astype(q.dtype)
    return out[:, 0] if squeeze else out


def paged_attention(q, k_pages, v_pages, page_table, context_lens,
                    scale: float | None = None,
                    k_scales=None, v_scales=None):
    """Dispatching entry point (what the model's decode path calls): the
    Pallas kernel on TPU or under force_interpret(); the XLA reference
    elsewhere — the same routing contract as
    F.scaled_dot_product_attention. ``k_scales``/``v_scales`` flow to
    whichever path runs (in-kernel dequant of quantized pools)."""
    if _HAS_PLTPU and (_on_tpu() or interpret_forced()):
        return paged_decode_attention(q, k_pages, v_pages, page_table,
                                      context_lens, scale=scale,
                                      k_scales=k_scales, v_scales=v_scales)
    return paged_attention_reference(q, k_pages, v_pages, page_table,
                                     context_lens, scale=scale,
                                     k_scales=k_scales, v_scales=v_scales)


# ---------------------------------------------------------------------------
# page-visit counter (the bench/test proof of the O(sum active tokens) claim)
# ---------------------------------------------------------------------------

def _visit_kernel(lens_ref, cnt_ref, *, page_size: int, pages_per_seq: int):
    b = pl.program_id(0)
    length = lens_ref[0, b]

    def body(p, n):
        first = p * page_size
        needed = _seg_blocks_can_touch(0, length - 1, first,
                                       first + page_size - 1)
        return n + needed.astype(jnp.float32)

    n = jax.lax.fori_loop(0, pages_per_seq, body, jnp.zeros((), jnp.float32))
    cnt_ref[0, 0] = n


def page_visit_counts(context_lens, page_size: int, pages_per_seq: int,
                      interpret: bool | None = None):
    """Per-sequence count of cache pages the decode kernel COMPUTES on,
    from the exact predicate it runs (`_seg_blocks_can_touch` over the page
    position range). int32 [B]; sum()/(B*pages_per_seq) is the visited
    fraction, == sum(ceil(len_b/ps)) / (B*pages_per_seq) — the serving
    bench's ragged-cost counter."""
    lens = jnp.asarray(context_lens, jnp.int32).reshape(1, -1)
    b = lens.shape[1]
    if interpret is None:
        interpret = _interpret_mode()
    kernel = functools.partial(_visit_kernel, page_size=page_size,
                               pages_per_seq=pages_per_seq)
    with _x64_off():
        cnt = pl.pallas_call(
            kernel,
            grid=(b,),
            in_specs=[pl.BlockSpec((1, b), lambda r: (0, 0))],
            out_specs=pl.BlockSpec((1, 1), lambda r: (r, 0)),
            out_shape=jax.ShapeDtypeStruct((b, 1), jnp.float32),
            interpret=interpret,
        )(lens)
    return cnt[:, 0].astype(jnp.int32)
