"""Pallas fused RMSNorm for TPU (forward + backward).

Reference analog: the fused normalization kernels the reference keeps in
phi/kernels/fusion (fused_rms_norm; fused attention/FFN epilogues).

TPU-native design: one row-block per grid step — the row loads into VMEM
once, the fp32 mean-square reduction, rsqrt and scale all happen in
registers, and the output stores once.

MEASURED (v5e, [8192, 2048] bf16, fwd+bwd): XLA's fused composite runs
~3x faster (~72us vs ~230us) because it fuses the norm into the
SURROUNDING ops, eliminating whole tensor round-trips a standalone kernel
must pay. This is why `nn.functional.rms_norm` defaults to the composite
(the CINN-replacement thesis of SURVEY §7.1) and Pallas is reserved for
attention, where XLA cannot avoid the [S, S] materialization. The kernel
stays as the guaranteed-fused form for isolated-norm workloads and as the
reference point for that measurement.

Backward recomputes rstd from x (cheaper than storing it for typical d) and
emits dx and a per-row-block partial dw that the caller sums — gradients
match the composite formula:
    dx = rstd * (dy*w - x * rstd^2/d * sum(dy*w*x, axis=-1))
    dw = sum over rows of dy * x * rstd

Falls back to interpreter mode off-TPU (fake-device pattern, SURVEY §4.4).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.pallas._compat import x64_off as _x64_off

__all__ = ["rmsnorm"]


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _rmsnorm_fwd_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    o_ref[...] = (x * rstd * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _rmsnorm_bwd_kernel(x_ref, w_ref, dy_ref, dx_ref, dw_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    d = x.shape[-1]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    dyw = dy * w
    proj = jnp.sum(dyw * x, axis=-1, keepdims=True) / d
    dx = rstd * (dyw - x * (rstd * rstd) * proj)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    # dw accumulates across the (sequential on TPU) row-block grid into one
    # (8, d) buffer — row 0 carries the sum, 8 rows satisfy tiling
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dw_ref[0, :] = dw_ref[0, :] + jnp.sum(dy * x * rstd, axis=0)


def _pick_rows(rows: int, d: int, block_rows: int) -> int:
    """Row-block through the shared tuning resolver when the caller left
    it at 0=auto: FLAGS_rmsnorm_block_rows > tuned entry > 256. Called
    identically from _fwd and _bwd (the resolver is deterministic, so
    both sides of the custom_vjp tile the same way)."""
    if block_rows > 0:
        return min(block_rows, rows)
    from paddle_tpu.tuning.blocks import resolve_blocks

    res = resolve_blocks("rmsnorm", {"rows": rows, "d": d},
                         default=lambda g: (256,))
    return min(int(res.values["block_rows"]), rows)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def rmsnorm(x, w, eps: float = 1e-6, block_rows: int = 0):
    """y = x * rsqrt(mean(x^2, -1) + eps) * w over the trailing axis.
    x: [rows, d] (callers flatten leading dims), w: [d]. block_rows 0
    resolves through tuning.blocks (flag > tuned > 256)."""
    return _fwd(x, w, eps, block_rows)[0]


def _fwd(x, w, eps, block_rows):
    rows, d = x.shape
    br = _pick_rows(rows, d, block_rows)
    interpret = not _on_tpu()
    # x64 mode (paddle int64 parity, enabled at package import) makes index
    # maps emit i64 constants Mosaic can't legalize — same guard as flash
    with _x64_off():
        out = pl.pallas_call(
            functools.partial(_rmsnorm_fwd_kernel, eps=eps),
            grid=(pl.cdiv(rows, br),),
            in_specs=[pl.BlockSpec((br, d), lambda i: (i, 0)),
                      pl.BlockSpec((1, d), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
            interpret=interpret,
        )(x, w.reshape(1, d))
    return out, (x, w)


def _bwd(eps, block_rows, res, dy):
    x, w = res
    rows, d = x.shape
    br = _pick_rows(rows, d, block_rows)
    n_blocks = pl.cdiv(rows, br)
    interpret = not _on_tpu()
    with _x64_off():
        dx, dw_acc = pl.pallas_call(
            functools.partial(_rmsnorm_bwd_kernel, eps=eps),
            grid=(n_blocks,),
            in_specs=[pl.BlockSpec((br, d), lambda i: (i, 0)),
                      pl.BlockSpec((1, d), lambda i: (0, 0)),
                      pl.BlockSpec((br, d), lambda i: (i, 0))],
            out_specs=[pl.BlockSpec((br, d), lambda i: (i, 0)),
                       pl.BlockSpec((8, d), lambda i: (0, 0))],
            out_shape=[jax.ShapeDtypeStruct((rows, d), x.dtype),
                       jax.ShapeDtypeStruct((8, d), jnp.float32)],
            interpret=interpret,
        )(x, w.reshape(1, d), dy)
    return dx, dw_acc[0].astype(w.dtype)


rmsnorm.defvjp(_fwd, _bwd)
