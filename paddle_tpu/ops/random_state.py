"""Global RNG state for eager ops.

Reference parity: phi `Generator` (paddle/phi/core/generator.h) + `paddle.seed`.
TPU-native: a counter-based splitting scheme over `jax.random` keys. Each draw
splits the global key, so eager randomness is reproducible from `seed()`. The
hybrid-parallel RNG tracker (reference fleet/layers/mpu/random.py:34) builds on
this in paddle_tpu.distributed.fleet.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "default_generator", "Generator"]


class Generator:
    """Key creation is LAZY: constructing a Generator (including the module
    default at import) must not initialize the XLA backend, or
    `jax.distributed.initialize` in init_parallel_env would be impossible
    afterwards (it requires no prior backend use in the process)."""

    def __init__(self, seed_: int = 0):
        self._lock = threading.Lock()
        self._seed = int(seed_)
        self._key = None

    def manual_seed(self, seed_: int):
        self._seed = int(seed_)
        with self._lock:
            self._key = None
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            return self._key

    def set_state(self, key):
        self._key = key


default_generator = Generator(0)


def seed(s: int):
    """`paddle.seed` analog: reseed the global generator."""
    default_generator.manual_seed(s)
    return default_generator
