"""Global RNG state for eager ops.

Reference parity: phi `Generator` (paddle/phi/core/generator.h) + `paddle.seed`.
TPU-native: a counter-based splitting scheme over `jax.random` keys. Each draw
splits the global key, so eager randomness is reproducible from `seed()`. The
hybrid-parallel RNG tracker (reference fleet/layers/mpu/random.py:34) builds on
this in paddle_tpu.distributed.fleet.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "default_generator", "Generator"]


class Generator:
    def __init__(self, seed_: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed_)

    def manual_seed(self, seed_: int):
        self._seed = int(seed_)
        self._key = jax.random.key(int(seed_))
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        return self._key

    def set_state(self, key):
        self._key = key


default_generator = Generator(0)


def seed(s: int):
    """`paddle.seed` analog: reseed the global generator."""
    default_generator.manual_seed(s)
    return default_generator
