"""Reduction ops (reference: python/paddle/tensor/math.py sum/mean/..., stat.py;
kernels paddle/phi/kernels/reduce_*). Reductions map 1:1 onto XLA reduce ops."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dtype import to_jax_dtype
from paddle_tpu.core.tensor import Tensor, apply_op

__all__ = [
    "sum", "mean", "max", "min", "prod", "argmax", "argmin", "all", "any",
    "logsumexp", "std", "var", "median", "amax", "amin", "count_nonzero",
    "nanmean", "nansum", "cumsum", "cumprod", "cummax", "cummin", "kthvalue",
    "mode",
]


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False):
    d = to_jax_dtype(dtype)
    return apply_op(
        lambda v: jnp.sum(v, axis=_axis(axis), dtype=d, keepdims=keepdim), x, name="sum"
    )


def nansum(x, axis=None, dtype=None, keepdim=False):
    d = to_jax_dtype(dtype)
    return apply_op(
        lambda v: jnp.nansum(v, axis=_axis(axis), dtype=d, keepdims=keepdim), x, name="nansum"
    )


def mean(x, axis=None, keepdim=False):
    return apply_op(lambda v: jnp.mean(v, axis=_axis(axis), keepdims=keepdim), x, name="mean")


def nanmean(x, axis=None, keepdim=False):
    return apply_op(lambda v: jnp.nanmean(v, axis=_axis(axis), keepdims=keepdim), x, name="nanmean")


def max(x, axis=None, keepdim=False):
    return apply_op(lambda v: jnp.max(v, axis=_axis(axis), keepdims=keepdim), x, name="max")


def min(x, axis=None, keepdim=False):
    return apply_op(lambda v: jnp.min(v, axis=_axis(axis), keepdims=keepdim), x, name="min")


amax = max
amin = min


def prod(x, axis=None, keepdim=False, dtype=None):
    d = to_jax_dtype(dtype)
    return apply_op(
        lambda v: jnp.prod(v, axis=_axis(axis), dtype=d, keepdims=keepdim), x, name="prod"
    )


def argmax(x, axis=None, keepdim=False, dtype="int64"):
    d = to_jax_dtype(dtype) or np.int64
    return apply_op(
        lambda v: jnp.argmax(v, axis=_axis(axis), keepdims=keepdim).astype(d), x, name="argmax"
    )


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    d = to_jax_dtype(dtype) or np.int64
    return apply_op(
        lambda v: jnp.argmin(v, axis=_axis(axis), keepdims=keepdim).astype(d), x, name="argmin"
    )


def all(x, axis=None, keepdim=False):
    return apply_op(lambda v: jnp.all(v, axis=_axis(axis), keepdims=keepdim), x, name="all")


def any(x, axis=None, keepdim=False):
    return apply_op(lambda v: jnp.any(v, axis=_axis(axis), keepdims=keepdim), x, name="any")


def logsumexp(x, axis=None, keepdim=False):
    import jax.scipy.special as jss

    return apply_op(
        lambda v: jss.logsumexp(v, axis=_axis(axis), keepdims=keepdim), x, name="logsumexp"
    )


def std(x, axis=None, unbiased=True, keepdim=False):
    return apply_op(
        lambda v: jnp.std(v, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
        name="std",
    )


def var(x, axis=None, unbiased=True, keepdim=False):
    return apply_op(
        lambda v: jnp.var(v, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        x,
        name="var",
    )


def median(x, axis=None, keepdim=False):
    return apply_op(lambda v: jnp.median(v, axis=_axis(axis), keepdims=keepdim), x, name="median")


def count_nonzero(x, axis=None, keepdim=False):
    return apply_op(
        lambda v: jnp.count_nonzero(v, axis=_axis(axis), keepdims=keepdim).astype(np.int64),
        x,
        name="count_nonzero",
    )


def cumsum(x, axis=None, dtype=None):
    d = to_jax_dtype(dtype)

    def f(v):
        if axis is None:
            return jnp.cumsum(v.reshape(-1), dtype=d)
        return jnp.cumsum(v, axis=int(axis), dtype=d)

    return apply_op(f, x, name="cumsum")


def cumprod(x, dim=None, dtype=None):
    d = to_jax_dtype(dtype)

    def f(v):
        if dim is None:
            return jnp.cumprod(v.reshape(-1), dtype=d)
        return jnp.cumprod(v, axis=int(dim), dtype=d)

    return apply_op(f, x, name="cumprod")


def _cum_compare(x, axis, cmp, name, dtype="int64"):
    """Running max/min with indices via one associative scan over
    (value, index) pairs — (values, indices) like the reference
    (python/paddle/tensor/math.py cummax/cummin). axis=None flattens."""
    import jax.lax as lax

    from paddle_tpu.core.dtype import to_jax_dtype

    idx_dtype = to_jax_dtype(dtype)

    def f(v):
        vv = v.reshape(-1) if axis is None else v
        a = 0 if axis is None else axis
        idx = lax.broadcasted_iota(idx_dtype, vv.shape, a % vv.ndim)

        def comb(l, r):
            lv, li = l
            rv, ri = r
            # ties keep the later index; NaN wins and then propagates —
            # both keep the combiner associative and match the reference
            take = jnp.isnan(rv) | (~jnp.isnan(lv) & cmp(rv, lv))
            return jnp.where(take, rv, lv), jnp.where(take, ri, li)

        return lax.associative_scan(comb, (vv, idx), axis=a)

    return apply_op(f, x, name=name)


def cummax(x, axis=None, dtype="int64"):
    return _cum_compare(x, axis, lambda r, l: r >= l, "cummax", dtype)


def cummin(x, axis=None, dtype="int64"):
    return _cum_compare(x, axis, lambda r, l: r <= l, "cummin", dtype)


def kthvalue(x, k, axis=-1, keepdim=False):
    def f(v):
        sorted_v = jnp.sort(v, axis=axis)
        idx = jnp.argsort(v, axis=axis)
        val = jnp.take(sorted_v, k - 1, axis=axis)
        ind = jnp.take(idx, k - 1, axis=axis)
        if keepdim:
            val = jnp.expand_dims(val, axis)
            ind = jnp.expand_dims(ind, axis)
        return val, ind.astype(np.int64)

    return apply_op(f, x, name="kthvalue")


def mode(x, axis=-1, keepdim=False):
    # Host-side (mode is a data-inspection op, not a training op).
    vals = np.asarray(x._value)

    def _mode1d(a):
        u, c = np.unique(a, return_counts=True)
        return u[np.argmax(c)]

    out = np.apply_along_axis(_mode1d, axis, vals)
    idx = np.argmax(vals == np.expand_dims(out, axis), axis=axis)
    if keepdim:
        out = np.expand_dims(out, axis)
        idx = np.expand_dims(idx, axis)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(idx.astype(np.int64)))
