"""Optimizers (reference: python/paddle/optimizer — 16 optimizers + lr.py).

Eager path: `step()` applies a jitted functional update per parameter (XLA
fuses the elementwise chain; buffers are donated so updates are in-place in
HBM). The same `_update(p, g, state) -> (p, state)` rules are reused by the
compiled train-step path and by the ZeRO sharding optimizers in
paddle_tpu.distributed.fleet (which shard `state` over the dp axis).
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.autograd import tape as _tape
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.clip import ClipGradBase
from paddle_tpu.optimizer import lr as lr_mod
from paddle_tpu.optimizer.lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
           "Adadelta", "RMSProp", "Lamb", "lr"]

lr = lr_mod


def _colocate(val, state: dict):
    """When ZeRO-sharded state lives on a multi-device mesh but the param is
    single-device (eager path), replicate the param onto the state's mesh so
    the fused update compiles (XLA then reduce-scatters internally)."""
    if not state:
        return val
    from jax.sharding import NamedSharding, PartitionSpec

    for sv in state.values():
        sh = getattr(sv, "sharding", None)
        if isinstance(sh, NamedSharding) and len(sv.devices()) > 1:
            if len(val.devices()) == 1:
                return jax.device_put(val, NamedSharding(sh.mesh, PartitionSpec()))
            return val
    return val


class Optimizer:
    """Base optimizer (reference: python/paddle/optimizer/optimizer.py)."""

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        if parameters is None:
            raise ValueError("parameters must be provided (eager mode)")
        self._params = list(parameters)
        self._param_groups = None
        if len(self._params) and isinstance(self._params[0], dict):
            self._param_groups = self._params
            self._params = [p for g in self._param_groups for p in g["params"]]
        self._lr = learning_rate
        self._weight_decay = self._parse_wd(weight_decay)
        self._grad_clip = grad_clip
        self._state: dict[int, dict] = {}
        self._step_count = 0
        self._use_master_weights = multi_precision
        self._jit_update = jax.jit(self._update, donate_argnums=(0, 2))

    @staticmethod
    def _parse_wd(weight_decay):
        if weight_decay is None:
            return 0.0
        if isinstance(weight_decay, (int, float)):
            return float(weight_decay)
        # L2Decay-style object with a coefficient
        return float(getattr(weight_decay, "_coeff", getattr(weight_decay, "coeff", 0.0)))

    # -- subclass interface -------------------------------------------------
    def _init_state(self, p: Tensor) -> dict:
        return {}

    def _update(self, pv, gv, state, lr, step):
        """Pure functional update: (param, grad, state, lr, step) -> (param', state')."""
        raise NotImplementedError

    # -- public API ---------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    def _parameter_list(self):
        return self._params

    def step(self):
        self._step_count += 1
        params_grads = [(p, p.grad) for p in self._params if (not p.stop_gradient and p.grad is not None)]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        cur_lr = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            sid = id(p)
            if sid not in self._state:
                self._state[sid] = self._init_state(p)
            gv = g._value
            if gv.dtype != p._value.dtype:
                gv = gv.astype(p._value.dtype)
            pv = _colocate(p._value, self._state[sid])
            gv = _colocate(gv, self._state[sid])
            new_p, new_state = self._jit_update(
                pv, gv, self._state[sid],
                jnp.asarray(cur_lr, jnp.float32), jnp.asarray(self._step_count, jnp.int32),
            )
            p._set_value(new_p)
            self._state[sid] = new_state

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._params]

    def clear_grad(self, set_to_zero=False):
        for p in self._params:
            p.clear_grad()

    clear_gradients = clear_grad

    def state_dict(self):
        out = {"step": self._step_count}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        for i, p in enumerate(self._params):
            st = self._state.get(id(p))
            if st:
                out[f"param_{i}"] = {k: np.asarray(v) for k, v in st.items()}
        return out

    def set_state_dict(self, state):
        self._step_count = state.get("step", 0)
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])
        for i, p in enumerate(self._params):
            key = f"param_{i}"
            if key in state:
                self._state[id(p)] = {k: jnp.asarray(v) for k, v in state[key].items()}


class SGD(Optimizer):
    def _update(self, pv, gv, state, lr, step):
        if self._weight_decay:
            gv = gv + self._weight_decay * pv
        return pv - lr.astype(pv.dtype) * gv, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        self._momentum = momentum
        self._nesterov = use_nesterov
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name, multi_precision)

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _update(self, pv, gv, state, lr, step):
        if self._weight_decay:
            gv = gv + self._weight_decay * pv
        v = self._momentum * state["velocity"] + gv
        if self._nesterov:
            upd = gv + self._momentum * v
        else:
            upd = v
        return pv - lr.astype(pv.dtype) * upd, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name, multi_precision)

    def _init_state(self, p):
        dt = jnp.float32 if self._use_master_weights else p._value.dtype
        st = {"m": jnp.zeros(p._value.shape, dt), "v": jnp.zeros(p._value.shape, dt)}
        if self._use_master_weights and p._value.dtype != jnp.float32:
            st["master"] = p._value.astype(jnp.float32)
        return st

    def _adam_core(self, pv32, gv32, state, lr, step):
        m = self._b1 * state["m"] + (1 - self._b1) * gv32
        v = self._b2 * state["v"] + (1 - self._b2) * jnp.square(gv32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - self._b1 ** t)
        vhat = v / (1 - self._b2 ** t)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._eps)
        return upd, m, v

    def _update(self, pv, gv, state, lr, step):
        master = state.get("master")
        p32 = master if master is not None else pv.astype(jnp.float32)
        g32 = gv.astype(jnp.float32)
        if self._weight_decay:  # Adam: L2 into grad (paddle semantics)
            g32 = g32 + self._weight_decay * p32
        upd, m, v = self._adam_core(p32, g32, state, lr, step)
        new32 = p32 - upd
        new_state = {"m": m, "v": v}
        if master is not None:
            new_state["master"] = new32
        return new32.astype(pv.dtype), new_state


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        self._apply_decay_fn = apply_decay_param_fun
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision, name)
        self._decay_flags = {
            id(p): (apply_decay_param_fun is None or apply_decay_param_fun(p.name or f"p{i}"))
            for i, p in enumerate(self._params)
        }
        self._jit_update_nodecay = jax.jit(functools.partial(self._update, decay=False),
                                           donate_argnums=(0, 2))

    def step(self):
        # route per-param decay flag through two jitted variants
        self._step_count += 1
        params_grads = [(p, p.grad) for p in self._params if (not p.stop_gradient and p.grad is not None)]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        cur_lr = jnp.asarray(self.get_lr(), jnp.float32)
        step = jnp.asarray(self._step_count, jnp.int32)
        for p, g in params_grads:
            sid = id(p)
            if sid not in self._state:
                self._state[sid] = self._init_state(p)
            gv = g._value
            if gv.dtype != p._value.dtype:
                gv = gv.astype(p._value.dtype)
            pv = _colocate(p._value, self._state[sid])
            gv = _colocate(gv, self._state[sid])
            fn = self._jit_update if self._decay_flags.get(sid, True) else self._jit_update_nodecay
            new_p, new_state = fn(pv, gv, self._state[sid], cur_lr, step)
            p._set_value(new_p)
            self._state[sid] = new_state

    def _update(self, pv, gv, state, lr, step, decay=True):
        master = state.get("master")
        p32 = master if master is not None else pv.astype(jnp.float32)
        g32 = gv.astype(jnp.float32)
        upd, m, v = self._adam_core(p32, g32, state, lr, step)
        new32 = p32 - upd
        if decay and self._weight_decay:
            new32 = new32 - lr * self._weight_decay * p32
        new_state = {"m": m, "v": v}
        if master is not None:
            new_state["master"] = new32
        return new32.astype(pv.dtype), new_state


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _init_state(self, p):
        return {"m": jnp.zeros_like(p._value, jnp.float32),
                "u": jnp.zeros_like(p._value, jnp.float32)}

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        if self._weight_decay:
            g32 = g32 + self._weight_decay * p32
        m = self._b1 * state["m"] + (1 - self._b1) * g32
        u = jnp.maximum(self._b2 * state["u"], jnp.abs(g32))
        t = step.astype(jnp.float32)
        new = p32 - lr / (1 - self._b1 ** t) * m / (u + self._eps)
        return new.astype(pv.dtype), {"m": m, "u": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        self._eps = epsilon
        self._init_acc = initial_accumulator_value
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _init_state(self, p):
        return {"acc": jnp.full(p._value.shape, self._init_acc, jnp.float32)}

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        if self._weight_decay:
            g32 = g32 + self._weight_decay * p32
        acc = state["acc"] + jnp.square(g32)
        new = p32 - lr * g32 / (jnp.sqrt(acc) + self._eps)
        return new.astype(pv.dtype), {"acc": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        self._eps, self._rho = epsilon, rho
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _init_state(self, p):
        return {"avg_sq": jnp.zeros_like(p._value, jnp.float32),
                "avg_upd": jnp.zeros_like(p._value, jnp.float32)}

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        if self._weight_decay:
            g32 = g32 + self._weight_decay * p32
        avg_sq = self._rho * state["avg_sq"] + (1 - self._rho) * jnp.square(g32)
        upd = jnp.sqrt(state["avg_upd"] + self._eps) / jnp.sqrt(avg_sq + self._eps) * g32
        avg_upd = self._rho * state["avg_upd"] + (1 - self._rho) * jnp.square(upd)
        return (p32 - lr * upd).astype(pv.dtype), {"avg_sq": avg_sq, "avg_upd": avg_upd}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        self._rho, self._eps, self._mom, self._centered = rho, epsilon, momentum, centered
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _init_state(self, p):
        st = {"ms": jnp.zeros_like(p._value, jnp.float32),
              "mom": jnp.zeros_like(p._value, jnp.float32)}
        if self._centered:
            st["mg"] = jnp.zeros_like(p._value, jnp.float32)
        return st

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        if self._weight_decay:
            g32 = g32 + self._weight_decay * p32
        ms = self._rho * state["ms"] + (1 - self._rho) * jnp.square(g32)
        if self._centered:
            mg = self._rho * state["mg"] + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._mom * state["mom"] + lr * g32 / denom
        out_state = {"ms": ms, "mom": mom}
        if self._centered:
            out_state["mg"] = mg
        return (p32 - mom).astype(pv.dtype), out_state


class Lamb(Optimizer):
    """reference: python/paddle/optimizer/lamb.py."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn
        super().__init__(learning_rate, parameters, None, grad_clip, name)

    def _init_state(self, p):
        return {"m": jnp.zeros_like(p._value, jnp.float32),
                "v": jnp.zeros_like(p._value, jnp.float32)}

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        m = self._b1 * state["m"] + (1 - self._b1) * g32
        v = self._b2 * state["v"] + (1 - self._b2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - self._b1 ** t)
        vhat = v / (1 - self._b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._eps) + self._lamb_wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (p32 - lr * trust * r).astype(pv.dtype), {"m": m, "v": v}
