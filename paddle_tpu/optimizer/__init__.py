"""Optimizers (reference: python/paddle/optimizer — 16 optimizers + lr.py).

Eager path: `step()` applies a jitted functional update per parameter (XLA
fuses the elementwise chain; buffers are donated so updates are in-place in
HBM). The same `_update(p, g, state) -> (p, state)` rules are reused by the
compiled train-step path and by the ZeRO sharding optimizers in
paddle_tpu.distributed.fleet (which shard `state` over the dp axis).
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.autograd import tape as _tape
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.clip import ClipGradBase
from paddle_tpu.optimizer import lr as lr_mod
from paddle_tpu.optimizer.lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
           "Adadelta", "RMSProp", "Lamb", "Lars", "ASGD", "NAdam", "RAdam",
           "Rprop", "LBFGS", "lr"]

lr = lr_mod


def _colocate(val, state: dict):
    """When ZeRO-sharded state lives on a multi-device mesh but the param is
    single-device (eager path), replicate the param onto the state's mesh so
    the fused update compiles (XLA then reduce-scatters internally)."""
    if not state:
        return val
    from jax.sharding import NamedSharding, PartitionSpec

    for sv in state.values():
        sh = getattr(sv, "sharding", None)
        if isinstance(sh, NamedSharding) and len(sv.devices()) > 1:
            if len(val.devices()) == 1:
                return jax.device_put(val, NamedSharding(sh.mesh, PartitionSpec()))
            return val
    return val


class Optimizer:
    """Base optimizer (reference: python/paddle/optimizer/optimizer.py)."""

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        if parameters is None:
            raise ValueError("parameters must be provided (eager mode)")
        self._params = list(parameters)
        self._param_groups = None
        if len(self._params) and isinstance(self._params[0], dict):
            self._param_groups = self._params
            self._params = [p for g in self._param_groups for p in g["params"]]
        self._lr = learning_rate
        self._weight_decay = self._parse_wd(weight_decay)
        self._grad_clip = grad_clip
        self._state: dict[int, dict] = {}
        self._step_count = 0
        self._use_master_weights = multi_precision
        self._jit_update = jax.jit(self._update, donate_argnums=(0, 2))

    def _parse_wd(self, weight_decay):
        self._wd_l1 = bool(getattr(weight_decay, "_l1", False))
        if weight_decay is None:
            return 0.0
        if isinstance(weight_decay, (int, float)):
            return float(weight_decay)
        # L1Decay/L2Decay-style object with a coefficient
        return float(getattr(weight_decay, "_coeff", getattr(weight_decay, "coeff", 0.0)))

    def _decayed(self, g, p):
        """Apply the configured regularizer to a gradient: L2 adds coeff*p,
        L1Decay adds coeff*sign(p)."""
        if not self._weight_decay:
            return g
        if self._wd_l1:
            return g + self._weight_decay * jnp.sign(p)
        return g + self._weight_decay * p

    # -- subclass interface -------------------------------------------------
    def _init_state(self, p: Tensor) -> dict:
        return {}

    def _update(self, pv, gv, state, lr, step):
        """Pure functional update: (param, grad, state, lr, step) -> (param', state')."""
        raise NotImplementedError

    # -- public API ---------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    def _parameter_list(self):
        return self._params

    def step(self):
        self._step_count += 1
        params_grads = [(p, p.grad) for p in self._params if (not p.stop_gradient and p.grad is not None)]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        cur_lr = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            sid = id(p)
            if sid not in self._state:
                self._state[sid] = self._init_state(p)
            gv = g._value
            if gv.dtype != p._value.dtype:
                gv = gv.astype(p._value.dtype)
            pv = _colocate(p._value, self._state[sid])
            gv = _colocate(gv, self._state[sid])
            new_p, new_state = self._jit_update(
                pv, gv, self._state[sid],
                jnp.asarray(cur_lr, jnp.float32), jnp.asarray(self._step_count, jnp.int32),
            )
            p._set_value(new_p)
            self._state[sid] = new_state

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from paddle_tpu.static.graph import _register_minimize

        if _register_minimize(self, loss):
            # recording into a static Program: Executor.run becomes the
            # jitted train step; nothing executes now
            return None, [(p, None) for p in self._params]
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._params]

    def clear_grad(self, set_to_zero=False):
        for p in self._params:
            p.clear_grad()

    clear_gradients = clear_grad

    def state_dict(self):
        out = {"step": self._step_count}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        for i, p in enumerate(self._params):
            st = self._state.get(id(p))
            if st:
                out[f"param_{i}"] = {k: np.asarray(v) for k, v in st.items()}
        return out

    def set_state_dict(self, state):
        self._step_count = state.get("step", 0)
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])
        for i, p in enumerate(self._params):
            key = f"param_{i}"
            if key in state:
                self._state[id(p)] = {k: jnp.asarray(v) for k, v in state[key].items()}


class SGD(Optimizer):
    def _update(self, pv, gv, state, lr, step):
        gv = self._decayed(gv, pv)
        return pv - lr.astype(pv.dtype) * gv, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        self._momentum = momentum
        self._nesterov = use_nesterov
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name, multi_precision)

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _update(self, pv, gv, state, lr, step):
        gv = self._decayed(gv, pv)
        v = self._momentum * state["velocity"] + gv
        if self._nesterov:
            upd = gv + self._momentum * v
        else:
            upd = v
        return pv - lr.astype(pv.dtype) * upd, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name, multi_precision)

    def _init_state(self, p):
        dt = jnp.float32 if self._use_master_weights else p._value.dtype
        st = {"m": jnp.zeros(p._value.shape, dt), "v": jnp.zeros(p._value.shape, dt)}
        if self._use_master_weights and p._value.dtype != jnp.float32:
            st["master"] = p._value.astype(jnp.float32)
        return st

    def _adam_core(self, pv32, gv32, state, lr, step):
        m = self._b1 * state["m"] + (1 - self._b1) * gv32
        v = self._b2 * state["v"] + (1 - self._b2) * jnp.square(gv32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - self._b1 ** t)
        vhat = v / (1 - self._b2 ** t)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._eps)
        return upd, m, v

    def _update(self, pv, gv, state, lr, step):
        master = state.get("master")
        p32 = master if master is not None else pv.astype(jnp.float32)
        g32 = gv.astype(jnp.float32)
        if self._weight_decay:  # Adam: L2 into grad (paddle semantics)
            g32 = g32 + self._weight_decay * p32
        upd, m, v = self._adam_core(p32, g32, state, lr, step)
        new32 = p32 - upd
        new_state = {"m": m, "v": v}
        if master is not None:
            new_state["master"] = new32
        return new32.astype(pv.dtype), new_state


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        self._apply_decay_fn = apply_decay_param_fun
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision, name)
        self._decay_flags = {
            id(p): (apply_decay_param_fun is None or apply_decay_param_fun(p.name or f"p{i}"))
            for i, p in enumerate(self._params)
        }
        self._jit_update_nodecay = jax.jit(functools.partial(self._update, decay=False),
                                           donate_argnums=(0, 2))

    def step(self):
        # route per-param decay flag through two jitted variants
        self._step_count += 1
        params_grads = [(p, p.grad) for p in self._params if (not p.stop_gradient and p.grad is not None)]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        cur_lr = jnp.asarray(self.get_lr(), jnp.float32)
        step = jnp.asarray(self._step_count, jnp.int32)
        for p, g in params_grads:
            sid = id(p)
            if sid not in self._state:
                self._state[sid] = self._init_state(p)
            gv = g._value
            if gv.dtype != p._value.dtype:
                gv = gv.astype(p._value.dtype)
            pv = _colocate(p._value, self._state[sid])
            gv = _colocate(gv, self._state[sid])
            fn = self._jit_update if self._decay_flags.get(sid, True) else self._jit_update_nodecay
            new_p, new_state = fn(pv, gv, self._state[sid], cur_lr, step)
            p._set_value(new_p)
            self._state[sid] = new_state

    def _update(self, pv, gv, state, lr, step, decay=True):
        master = state.get("master")
        p32 = master if master is not None else pv.astype(jnp.float32)
        g32 = gv.astype(jnp.float32)
        upd, m, v = self._adam_core(p32, g32, state, lr, step)
        new32 = p32 - upd
        if decay and self._weight_decay:
            new32 = new32 - lr * self._weight_decay * p32
        new_state = {"m": m, "v": v}
        if master is not None:
            new_state["master"] = new32
        return new32.astype(pv.dtype), new_state


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _init_state(self, p):
        return {"m": jnp.zeros_like(p._value, jnp.float32),
                "u": jnp.zeros_like(p._value, jnp.float32)}

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        g32 = self._decayed(g32, p32)
        m = self._b1 * state["m"] + (1 - self._b1) * g32
        u = jnp.maximum(self._b2 * state["u"], jnp.abs(g32))
        t = step.astype(jnp.float32)
        new = p32 - lr / (1 - self._b1 ** t) * m / (u + self._eps)
        return new.astype(pv.dtype), {"m": m, "u": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        self._eps = epsilon
        self._init_acc = initial_accumulator_value
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _init_state(self, p):
        return {"acc": jnp.full(p._value.shape, self._init_acc, jnp.float32)}

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        g32 = self._decayed(g32, p32)
        acc = state["acc"] + jnp.square(g32)
        new = p32 - lr * g32 / (jnp.sqrt(acc) + self._eps)
        return new.astype(pv.dtype), {"acc": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        self._eps, self._rho = epsilon, rho
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _init_state(self, p):
        return {"avg_sq": jnp.zeros_like(p._value, jnp.float32),
                "avg_upd": jnp.zeros_like(p._value, jnp.float32)}

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        g32 = self._decayed(g32, p32)
        avg_sq = self._rho * state["avg_sq"] + (1 - self._rho) * jnp.square(g32)
        upd = jnp.sqrt(state["avg_upd"] + self._eps) / jnp.sqrt(avg_sq + self._eps) * g32
        avg_upd = self._rho * state["avg_upd"] + (1 - self._rho) * jnp.square(upd)
        return (p32 - lr * upd).astype(pv.dtype), {"avg_sq": avg_sq, "avg_upd": avg_upd}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        self._rho, self._eps, self._mom, self._centered = rho, epsilon, momentum, centered
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _init_state(self, p):
        st = {"ms": jnp.zeros_like(p._value, jnp.float32),
              "mom": jnp.zeros_like(p._value, jnp.float32)}
        if self._centered:
            st["mg"] = jnp.zeros_like(p._value, jnp.float32)
        return st

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        g32 = self._decayed(g32, p32)
        ms = self._rho * state["ms"] + (1 - self._rho) * jnp.square(g32)
        if self._centered:
            mg = self._rho * state["mg"] + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._mom * state["mom"] + lr * g32 / denom
        out_state = {"ms": ms, "mom": mom}
        if self._centered:
            out_state["mg"] = mg
        return (p32 - mom).astype(pv.dtype), out_state


class Lamb(Optimizer):
    """reference: python/paddle/optimizer/lamb.py."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn
        super().__init__(learning_rate, parameters, None, grad_clip, name)

    def _init_state(self, p):
        return {"m": jnp.zeros_like(p._value, jnp.float32),
                "v": jnp.zeros_like(p._value, jnp.float32)}

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        m = self._b1 * state["m"] + (1 - self._b1) * g32
        v = self._b2 * state["v"] + (1 - self._b2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - self._b1 ** t)
        vhat = v / (1 - self._b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._eps) + self._lamb_wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (p32 - lr * trust * r).astype(pv.dtype), {"m": m, "v": v}


class Lars(Momentum):
    """LARS momentum: layer-wise trust-ratio scaled learning rate
    (reference: paddle lars_momentum op, incubate LarsMomentumOptimizer)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, epsilon=1e-8,
                 grad_clip=None, name=None, multi_precision=False):
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon
        super().__init__(learning_rate, momentum, parameters, False, None,
                         grad_clip, name, multi_precision)

    def _update(self, pv, gv, state, lr, step):
        p32 = pv.astype(jnp.float32)
        g32 = gv.astype(jnp.float32)
        p_norm = jnp.linalg.norm(p32)
        g_norm = jnp.linalg.norm(g32)
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            self._lars_coeff * p_norm / (g_norm + self._lars_wd * p_norm + self._eps),
            1.0)
        upd = g32 + self._lars_wd * p32
        v = self._momentum * state["velocity"].astype(jnp.float32) + lr * local_lr * upd
        return (p32 - v).astype(pv.dtype), {"velocity": v}


class ASGD(Optimizer):
    """Averaged SGD (reference: python/paddle/optimizer/asgd.py): plain SGD
    steps plus a running average of the iterates; `averaged_value(p)` exposes
    the Polyak average for evaluation."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, t0=0, name=None,
                 multi_precision=False):
        self._t0 = t0
        self._batch_num = max(1, int(batch_num))
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _init_state(self, p):
        # fresh buffer: astype of an f32 param would ALIAS it, and the jitted
        # update donates both the param and the state
        n = self._batch_num
        return {"ax": jnp.array(p._value, jnp.float32, copy=True),
                "d": jnp.zeros(p._value.shape, jnp.float32),
                "ys": jnp.zeros((n,) + tuple(p._value.shape), jnp.float32)}

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        g32 = self._decayed(g32, p32)
        # reference asgd op: update with the average of the last batch_num
        # grads (circular window d = d - oldest + g)
        n = self._batch_num
        pos = (step.astype(jnp.int32) - 1) % n
        old = jax.lax.dynamic_index_in_dim(state["ys"], pos, 0, keepdims=False)
        d = state["d"] - old + g32
        ys = jax.lax.dynamic_update_index_in_dim(state["ys"], g32, pos, 0)
        denom = jnp.minimum(step.astype(jnp.float32), float(n))
        new_p = p32 - lr * d / denom
        t = step.astype(jnp.float32)
        mu = 1.0 / jnp.maximum(1.0, t - self._t0)
        ax = state["ax"] + mu * (new_p - state["ax"])
        return new_p.astype(pv.dtype), {"ax": ax, "d": d, "ys": ys}

    def averaged_value(self, p):
        """Polyak-averaged iterate — a COPY (the live state buffer is donated
        to the next step's jitted update)."""
        st = self._state.get(id(p))
        return Tensor(jnp.array(st["ax"], copy=True)) if st else p


class NAdam(Optimizer):
    """Nesterov-momentum Adam (reference: python/paddle/optimizer/nadam.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 momentum_decay=0.004, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        self._psi = momentum_decay
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _init_state(self, p):
        return {"m": jnp.zeros_like(p._value, jnp.float32),
                "v": jnp.zeros_like(p._value, jnp.float32),
                "mu_prod": jnp.ones((), jnp.float32)}

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        g32 = self._decayed(g32, p32)
        t = step.astype(jnp.float32)
        mu_t = self._b1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = self._b1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mu_prod = state["mu_prod"] * mu_t
        m = self._b1 * state["m"] + (1 - self._b1) * g32
        v = self._b2 * state["v"] + (1 - self._b2) * jnp.square(g32)
        mhat = (mu_t1 * m / (1 - mu_prod * mu_t1)
                + (1 - mu_t) * g32 / (1 - mu_prod))
        vhat = v / (1 - self._b2 ** t)
        new_p = p32 - lr * mhat / (jnp.sqrt(vhat) + self._eps)
        return new_p.astype(pv.dtype), {"m": m, "v": v, "mu_prod": mu_prod}


class RAdam(Optimizer):
    """Rectified Adam (reference: python/paddle/optimizer/radam.py): variance
    rectification switches between adaptive and plain momentum updates."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None,
                 multi_precision=False):
        self._b1, self._b2, self._eps = beta1, beta2, epsilon
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _init_state(self, p):
        return {"m": jnp.zeros_like(p._value, jnp.float32),
                "v": jnp.zeros_like(p._value, jnp.float32)}

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        g32 = self._decayed(g32, p32)
        t = step.astype(jnp.float32)
        m = self._b1 * state["m"] + (1 - self._b1) * g32
        v = self._b2 * state["v"] + (1 - self._b2) * jnp.square(g32)
        mhat = m / (1 - self._b1 ** t)
        rho_inf = 2.0 / (1 - self._b2) - 1.0
        b2t = self._b2 ** t
        rho_t = rho_inf - 2.0 * t * b2t / (1 - b2t)
        r_num = (rho_t - 4) * (rho_t - 2) * rho_inf
        r_den = (rho_inf - 4) * (rho_inf - 2) * jnp.maximum(rho_t, self._eps)
        r_t = jnp.sqrt(jnp.maximum(r_num / r_den, 0.0))
        vhat = jnp.sqrt(v / (1 - b2t)) + self._eps
        adaptive = r_t * mhat / vhat
        new_p = jnp.where(rho_t > 5.0, p32 - lr * adaptive, p32 - lr * mhat)
        return new_p.astype(pv.dtype), {"m": m, "v": v}


class Rprop(Optimizer):
    """Resilient backprop (reference: python/paddle/optimizer/rprop.py):
    per-weight step sizes grown on sign agreement, shrunk on disagreement
    (full-batch regime)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None, name=None,
                 multi_precision=False):
        self._eta_minus, self._eta_plus = etas
        self._lr_min, self._lr_max = learning_rate_range
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)

    def _init_state(self, p):
        return {"prev_grad": jnp.zeros_like(p._value, jnp.float32),
                "step_size": jnp.full(p._value.shape, float(self.get_lr()),
                                      jnp.float32)}

    def _update(self, pv, gv, state, lr, step):
        g32 = gv.astype(jnp.float32)
        p32 = pv.astype(jnp.float32)
        sign = jnp.sign(g32 * state["prev_grad"])
        factor = jnp.where(sign > 0, self._eta_plus,
                           jnp.where(sign < 0, self._eta_minus, 1.0))
        step_size = jnp.clip(state["step_size"] * factor, self._lr_min, self._lr_max)
        # on sign flip: revert-style zeroed gradient (iRprop-)
        g_eff = jnp.where(sign < 0, 0.0, g32)
        new_p = p32 - step_size * jnp.sign(g_eff)
        return new_p.astype(pv.dtype), {"prev_grad": g_eff, "step_size": step_size}


class LBFGS(Optimizer):
    """L-BFGS quasi-Newton optimizer (reference: python/paddle/optimizer/
    lbfgs.py). `step(closure)` re-evaluates loss+grads up to `max_iter` times
    per call, maintaining a `history_size` window of (s, y) pairs and the
    two-loop-recursion direction; optional backtracking line search."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._max_iter = max_iter
        self._max_eval = max_eval if max_eval is not None else int(max_iter * 1.25)
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._hist_size = history_size
        self._line_search = line_search_fn
        self._s_hist: list = []
        self._y_hist: list = []
        self._rho_hist: list = []
        self._prev_flat = None
        self._prev_grad = None
        self._n_eval = 0

    # flat-vector helpers over the whole parameter list
    def _flat_params(self):
        return jnp.concatenate([p._value.reshape(-1).astype(jnp.float32)
                                for p in self._params])

    def _flat_grads(self):
        params_grads = [(p, p.grad) for p in self._params]
        if self._grad_clip is not None:
            clipped = dict((id(p), g) for p, g in self._grad_clip(
                [(p, g) for p, g in params_grads if g is not None]))
            params_grads = [(p, clipped.get(id(p), g)) for p, g in params_grads]
        out = []
        for p, g in params_grads:
            gv = g._value if g is not None else jnp.zeros_like(p._value)
            out.append(gv.reshape(-1).astype(jnp.float32))
        return jnp.concatenate(out)

    def _assign_flat(self, flat):
        ofs = 0
        for p in self._params:
            n = p._value.size
            p._set_value(flat[ofs:ofs + n].reshape(p._value.shape).astype(p._value.dtype))
            ofs += n

    def _direction(self, g):
        """Two-loop recursion: H·g with implicit inverse-Hessian history."""
        q = g
        alphas = []
        for s, y, rho in zip(reversed(self._s_hist), reversed(self._y_hist),
                             reversed(self._rho_hist)):
            a = rho * jnp.dot(s, q)
            alphas.append(a)
            q = q - a * y
        if self._y_hist:
            y_last, s_last = self._y_hist[-1], self._s_hist[-1]
            gamma = jnp.dot(s_last, y_last) / jnp.maximum(jnp.dot(y_last, y_last), 1e-10)
            q = gamma * q
        for (s, y, rho), a in zip(zip(self._s_hist, self._y_hist, self._rho_hist),
                                  reversed(alphas)):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        return -q

    def step(self, closure=None):
        self._step_count += 1
        if closure is None:
            # grads already populated by a prior backward: one qN update
            return self._one_iteration(None)
        loss = None
        self._n_eval = 0
        for _ in range(self._max_iter):
            loss = self._one_iteration(closure)
            if loss is None or self._n_eval >= self._max_eval:
                break
            g = self._flat_grads()
            if float(jnp.max(jnp.abs(g))) <= self._tol_grad:
                break
        return loss

    def _eval_closure(self, closure):
        self._n_eval += 1
        self.clear_grad()
        loss = closure()
        if hasattr(loss, "backward") and all(
                p.grad is None for p in self._params):
            loss.backward()
        return loss

    def _one_iteration(self, closure):
        if closure is not None:
            loss = self._eval_closure(closure)
        else:
            loss = None
        x = self._flat_params()
        g = self._flat_grads()
        g = self._decayed(g, x)
        if self._prev_flat is not None:
            s = x - self._prev_flat
            y = g - self._prev_grad
            sy = float(jnp.dot(s, y))
            if sy > 1e-10:
                self._s_hist.append(s)
                self._y_hist.append(y)
                self._rho_hist.append(1.0 / sy)
                if len(self._s_hist) > self._hist_size:
                    self._s_hist.pop(0)
                    self._y_hist.pop(0)
                    self._rho_hist.pop(0)
        d = self._direction(g)
        lr = self.get_lr()
        if not self._s_hist:  # first step: conservative scaled descent
            lr = min(1.0, 1.0 / max(float(jnp.sum(jnp.abs(g))), 1e-10)) * lr
        if self._line_search == "strong_wolfe" and closure is not None:
            lr = self._backtrack(closure, x, g, d, lr)
        self._prev_flat = x
        self._prev_grad = g
        self._assign_flat(x + lr * d)
        if float(jnp.max(jnp.abs(lr * d))) <= self._tol_change:
            return None
        return loss

    def state_dict(self):
        out = super().state_dict()
        out["lbfgs"] = {
            "s": [np.asarray(s) for s in self._s_hist],
            "y": [np.asarray(y) for y in self._y_hist],
            "rho": list(self._rho_hist),
            "prev_flat": None if self._prev_flat is None else np.asarray(self._prev_flat),
            "prev_grad": None if self._prev_grad is None else np.asarray(self._prev_grad),
        }
        return out

    def set_state_dict(self, state):
        super().set_state_dict(state)
        lb = state.get("lbfgs")
        if lb:
            self._s_hist = [jnp.asarray(s) for s in lb["s"]]
            self._y_hist = [jnp.asarray(y) for y in lb["y"]]
            self._rho_hist = list(lb["rho"])
            self._prev_flat = (None if lb["prev_flat"] is None
                               else jnp.asarray(lb["prev_flat"]))
            self._prev_grad = (None if lb["prev_grad"] is None
                               else jnp.asarray(lb["prev_grad"]))

    def _backtrack(self, closure, x, g, d, lr, c1=1e-4, shrink=0.5, tries=10):
        """Armijo backtracking (stand-in for the reference's strong-wolfe).
        The closure runs normally (it does its own backward); only the loss
        value is consumed here, and params are restored afterwards. With
        weight_decay, the wd penalty 0.5*wd*||x||^2 is added to the observed
        losses so the sufficient-decrease test matches the wd-augmented
        gradient used for `g` and `d`."""
        def f_at(flat):
            self._assign_flat(flat)
            val = float(self._eval_closure(closure))
            if self._weight_decay:
                if self._wd_l1:
                    val += self._weight_decay * float(jnp.sum(jnp.abs(flat)))
                else:
                    val += 0.5 * self._weight_decay * float(jnp.dot(flat, flat))
            return val

        gtd = float(jnp.dot(g, d))
        f0 = f_at(x)
        for _ in range(tries):
            if self._n_eval >= self._max_eval:
                break
            f1 = f_at(x + lr * d)
            if f1 <= f0 + c1 * lr * gtd:
                break
            lr *= shrink
        self._assign_flat(x)
        return lr
