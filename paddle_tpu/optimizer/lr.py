"""LR schedulers (reference: python/paddle/optimizer/lr.py)."""
from __future__ import annotations

import math

__all__ = [
    "LRScheduler", "NoamDecay", "ExponentialDecay", "NaturalExpDecay",
    "InverseTimeDecay", "PolynomialDecay", "LinearWarmup", "PiecewiseDecay",
    "CosineAnnealingDecay", "MultiStepDecay", "StepDecay", "LambdaDecay",
    "ReduceOnPlateau", "OneCycleLR", "CyclicLR",
]


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.last_lr = self.base_lr
        self.verbose = verbose
        self.step()

    def get_lr(self) -> float:
        raise NotImplementedError

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()

    def __call__(self) -> float:
        return self.last_lr

    def state_dict(self):
        return {k: v for k, v in self.__dict__.items() if not k.startswith("_")}

    def set_state_dict(self, state):
        self.__dict__.update(state)

    def get_last_lr(self):
        return self.last_lr


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        return self.base_lr * (self.d_model ** -0.5) * min(step ** -0.5, step * self.warmup_steps ** -1.5)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** max(self.last_epoch, 0)


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * max(self.last_epoch, 0))


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * max(self.last_epoch, 0))


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0, cycle=False,
                 last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        if self.cycle:
            div = math.ceil(step / self.decay_steps) or 1
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            step = min(step, decay_steps)
        return (self.base_lr - self.end_lr) * ((1 - step / decay_steps) ** self.power) + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr, last_epoch=-1, verbose=False):
        self.lr_sched = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.target = learning_rate if not self.lr_sched else None
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(start_lr, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        if step < self.warmup_steps:
            return (self.end_lr - self.start_lr) * step / self.warmup_steps + self.start_lr
        if self.lr_sched:
            self.lr_sched.step()
            return self.lr_sched()
        return self.target


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = boundaries
        self.values = values
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        for i, b in enumerate(self.boundaries):
            if step < b:
                return self.values[i]
        return self.values[-1]


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1, verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        return self.eta_min + (self.base_lr - self.eta_min) * (1 + math.cos(math.pi * step / self.T_max)) / 2


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1, verbose=False):
        self.milestones = milestones
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        return self.base_lr * self.gamma ** sum(step >= m for m in self.milestones)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1, verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (max(self.last_epoch, 0) // self.step_size)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(max(self.last_epoch, 0))


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10, threshold=1e-4,
                 threshold_mode="rel", cooldown=0, min_lr=0, epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self._lr = float(learning_rate)
        self.base_lr = float(learning_rate)
        self.last_lr = self._lr
        self.last_epoch = 0

    def get_lr(self):
        return self._lr

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        m = float(metrics.item() if hasattr(metrics, "item") else metrics)
        better = (
            self.best is None
            or (self.mode == "min" and m < self.best - self.threshold)
            or (self.mode == "max" and m > self.best + self.threshold)
        )
        if better:
            self.best = m
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        elif self.num_bad > self.patience:
            self._lr = max(self._lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad = 0
        self.last_lr = self._lr


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3, anneal_strategy="cos",
                 three_phase=False, last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        super().__init__(self.initial_lr, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        up = int(self.total_steps * self.phase_pct)
        if step <= up and up > 0:
            pct = step / up
            return self.initial_lr + (self.max_lr - self.initial_lr) * (1 - math.cos(math.pi * pct)) / 2
        down = self.total_steps - up
        pct = min((step - up) / max(down, 1), 1.0)
        return self.end_lr + (self.max_lr - self.end_lr) * (1 + math.cos(math.pi * pct)) / 2


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate, step_size_up, step_size_down=None,
                 mode="triangular", exp_gamma=1.0, scale_fn=None, scale_mode="cycle",
                 last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.up = step_size_up
        self.down = step_size_down or step_size_up
        self.mode = mode
        self.exp_gamma = exp_gamma
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 0)
        cycle_len = self.up + self.down
        cycle = step // cycle_len
        pos = step % cycle_len
        if pos < self.up:
            pct = pos / self.up
        else:
            pct = 1 - (pos - self.up) / self.down
        amp = self.max_lr - self.base_lr
        if self.mode == "triangular2":
            amp = amp / (2 ** cycle)
        elif self.mode == "exp_range":
            amp = amp * (self.exp_gamma ** step)
        return self.base_lr + amp * pct


class MultiplicativeDecay(LRScheduler):
    """reference lr.py MultiplicativeDecay (:1821): lr multiplies by
    lr_lambda(epoch) cumulatively each epoch."""

    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        lr = self.base_lr
        for e in range(1, max(self.last_epoch, 0) + 1):
            lr *= self.lr_lambda(e)
        return lr


class LinearLR(LRScheduler):
    """reference lr.py LinearLR (:2355): linearly anneal the multiplier from
    start_factor to end_factor over total_steps."""

    def __init__(self, learning_rate, total_steps, start_factor=1.0 / 3,
                 end_factor=1.0, last_epoch=-1, verbose=False):
        if total_steps <= 0:
            raise ValueError("total_steps must be positive")
        self.total_steps = total_steps
        self.start_factor = start_factor
        self.end_factor = end_factor
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        e = max(self.last_epoch, 0)
        if e >= self.total_steps:
            return self.base_lr * self.end_factor
        frac = e / self.total_steps
        factor = self.start_factor + (self.end_factor - self.start_factor) * frac
        return self.base_lr * factor


class CosineAnnealingWarmRestarts(LRScheduler):
    """reference lr.py CosineAnnealingWarmRestarts (:2474): SGDR cosine
    cycles restarting every T_i epochs with T_{i+1} = T_i * T_mult."""

    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0.0,
                 last_epoch=-1, verbose=False):
        if T_0 <= 0 or T_mult < 1:
            raise ValueError("T_0 must be positive and T_mult >= 1")
        self.T_0 = T_0
        self.T_mult = T_mult
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        import math as _m

        e = max(self.last_epoch, 0)
        t_i, t_cur = self.T_0, e
        while t_cur >= t_i:
            t_cur -= t_i
            t_i *= self.T_mult
        return (self.eta_min + (self.base_lr - self.eta_min)
                * (1 + _m.cos(_m.pi * t_cur / t_i)) / 2)


__all__ += ["MultiplicativeDecay", "LinearLR", "CosineAnnealingWarmRestarts"]
