"""SPMD compilation layer: mesh-sharded whole-program train steps.

This is the TPU-native replacement for the reference's static-graph executor +
distributed passes stack (SURVEY §3.5, §2.3): parallelism is expressed as
shardings on ONE compiled XLA program instead of per-rank programs + NCCL.
"""
from paddle_tpu.parallel.train_step import CompiledTrainStep, functional_call  # noqa: F401
from paddle_tpu.parallel import pipeline_schedules  # noqa: F401
from paddle_tpu.parallel.pipeline import PipelinedTrainStep  # noqa: F401
from paddle_tpu.parallel.zero_bubble import ZBH1PipelinedStep  # noqa: F401
from paddle_tpu.parallel.scan_layers import (  # noqa: F401
    REMAT_POLICIES, normalize_remat, remat_wrap, scan_layer_stack,
)
from paddle_tpu.parallel.segments import (  # noqa: F401
    current_segment_ctx, segment_execution,
)
