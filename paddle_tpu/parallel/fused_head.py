"""Head-stage loss fusion shared by the compiled runtimes.

The pipelined runtimes (PipelinedTrainStep 1F1B, ZBH1PipelinedStep) evaluate
`loss_fn(head(x), labels)` on the last stage. When the head ends in a plain
vocab projection and the loss is a recognizable hard-label softmax-CE, that
pair lowers to the chunked fused kernel
(`paddle_tpu.ops.pallas.fused_ce.fused_linear_cross_entropy_loss`): the
`[tokens, vocab]` logits never exist in forward or backward, and under a
bound "mp" axis the softmax stats reduce Megatron-style over the vocab
shards. Escape hatch: the `use_fused_head_loss` flag (read when the step
program is traced).

Fusion protocol (both conditions opt the head in):
  * the head layer implements ``forward_features(x)`` — everything it does
    BEFORE the final projection (`head(x) == head.lm_head(
    head.forward_features(x))` must hold) — and exposes that projection as
    ``head.lm_head`` (an `nn.Linear` or a `ColumnParallelLinear` that keeps
    its vocab shard local, i.e. gather_output=False under mp);
  * the loss_fn is an `nn.CrossEntropyLoss` in its fusable configuration, a
    `LlamaPretrainingCriterion`, or any callable carrying a
    ``_fused_ce_spec`` dict (keys: ignore_index, label_smoothing,
    reduction in {"mean", "sum", "mean_all"} — "mean" averages over
    non-ignored tokens like F.cross_entropy, "mean_all" over every token
    like `ParallelCrossEntropy(...).mean()`).
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["fused_ce_spec", "fused_head_spec", "fused_head_loss"]


def fused_ce_spec(loss_fn) -> dict | None:
    """The fused-CE config of `loss_fn(logits, labels)`, or None when the
    loss is not a recognizable hard-label softmax-CE."""
    spec = getattr(loss_fn, "_fused_ce_spec", None)
    if spec is not None:
        return dict(spec)
    from paddle_tpu.nn.layer.loss import CrossEntropyLoss

    if isinstance(loss_fn, CrossEntropyLoss):
        if (loss_fn.weight is None and not loss_fn.soft_label
                and loss_fn.use_softmax and loss_fn.axis == -1
                and loss_fn.use_fused is not False
                and loss_fn.reduction in ("mean", "sum")):
            return dict(ignore_index=loss_fn.ignore_index,
                        label_smoothing=loss_fn.label_smoothing,
                        reduction=loss_fn.reduction)
        return None
    from paddle_tpu.models.llama import LlamaPretrainingCriterion

    if isinstance(loss_fn, LlamaPretrainingCriterion):
        if loss_fn.parallel_ce is not None:
            # per-token parallel CE (ignored tokens contribute 0) then
            # .mean() over EVERY token — preserve that reduction exactly
            return dict(ignore_index=loss_fn.parallel_ce.ignore_index,
                        label_smoothing=0.0, reduction="mean_all")
        return dict(ignore_index=-100, label_smoothing=0.0, reduction="mean")
    return None


def fused_head_spec(head, loss_fn) -> dict | None:
    """The joint head+loss fusion spec for a (head layer, loss_fn) pair, or
    None when the pair must run the unfused `loss_fn(head(x), labels)`."""
    from paddle_tpu.core.flags import flag

    if not flag("use_fused_head_loss"):
        return None
    spec = fused_ce_spec(loss_fn)
    if spec is None:
        return None
    proj = getattr(head, "lm_head", None)
    if (getattr(head, "forward_features", None) is None or proj is None
            or getattr(proj, "weight", None) is None):
        return None
    from paddle_tpu.distributed.fleet.layers.mpu.mp_layers import (
        ColumnParallelLinear)
    from paddle_tpu.distributed.fleet.layers.mpu.mp_ops import MP_AXIS
    from paddle_tpu.distributed.mesh import mesh_axis_size

    if (isinstance(proj, ColumnParallelLinear) and proj.gather_output
            and mesh_axis_size(MP_AXIS) > 1):
        # gathered full-vocab output: the unfused loss sees [.., V] logits;
        # keep that path rather than re-deriving shard-local semantics
        return None
    return spec


def reduce_fused_nll(nll, labels_flat, spec):
    """Reduce per-token fp32 fused-CE losses per the spec's reduction."""
    red = spec.get("reduction", "mean")
    if red == "mean_all":
        return jnp.mean(nll)
    from paddle_tpu.nn.functional import _fused_ce_reduce

    valid = labels_flat != spec.get("ignore_index", -100)
    return _fused_ce_reduce(nll, valid, red, nll.shape, nll.dtype)


def fused_head_loss(head, head_vals, x, labels, spec):
    """Scalar fp32 `loss_fn(head(x), labels)` via the chunked fused kernel,
    with `head_vals` temporarily bound as the head's parameters. x/labels
    are raw arrays; never builds the [tokens, vocab] logits."""
    from paddle_tpu.parallel.train_step import functional_call

    feat = functional_call(head, head_vals, (x,), method="forward_features")
    fv = feat._value if isinstance(feat, Tensor) else feat
    proj = head.lm_head

    def _bound_val(param):
        # the traced value bound to `param` (positional, like the swap
        # functional_call performs) — the layer attribute itself holds the
        # UNBOUND concrete value outside the call
        return next(v for p, v in zip(head.parameters(), head_vals)
                    if p is param)

    w = _bound_val(proj.weight)
    b = (_bound_val(proj.bias)
         if getattr(proj, "bias", None) is not None else None)
    from paddle_tpu.ops.pallas.fused_ce import fused_linear_cross_entropy_loss

    lab = labels
    if lab.ndim == fv.ndim:
        lab = jnp.squeeze(lab, -1)
    flat = fv.reshape(-1, fv.shape[-1])
    labf = lab.reshape(-1)
    nll = fused_linear_cross_entropy_loss(
        flat, w, labf, b,
        ignore_index=spec.get("ignore_index", -100),
        label_smoothing=spec.get("label_smoothing", 0.0),
        z_loss=spec.get("z_loss", 0.0), mp_axis="auto")
    return reduce_fused_nll(nll, labf, spec)
