"""Compiled pipeline parallelism: the whole schedule is ONE XLA program.

Reference analog: Fleet's 1F1B runtime (pipeline_parallel.py:459
forward_backward_pipeline) + batched p2p (p2p_communication.py:322) + the
static pipeline passes / FleetExecutor (SURVEY §2.1).

TPU-native design (the "pipelining via collective_permute" recipe of the
scaling book): inside `shard_map` over the "pp" mesh axis each rank holds ONE
stage's parameters (stacked pytree, leading dim = pp). A `lax.scan` streams
M microbatches through T = M + S - 1 ticks; activations hop to the next stage
with `lax.ppermute` over ICI. Differentiating through the scan gives the
reverse (backward) pipeline automatically — XLA schedules fwd/bwd ticks and
overlaps the permutes with compute, which is exactly the 1F1B overlap the
reference hand-codes with comm streams. Tensor parallelism composes: inside
shard_map the "mp" axis is bound, so the mpu layers' explicit collectives
(identity/psum pairs, mp_ops.py) activate with local shards.

Microbatch loss masking: each rank computes every tick, but only
(rank == S-1, valid mb) ticks contribute loss; invalid ticks are masked out.
The embedding/head run in-pipeline on the first/last stage's rank.
"""
from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.fleet import rng as fleet_rng
from paddle_tpu.distributed.mesh import get_mesh
from paddle_tpu.parallel.train_step import _param_pspec, functional_call

__all__ = ["PipelinedTrainStep"]


def _stack_params(stages):
    """Stack homogeneous per-stage param lists: list[stage][param] -> list[param stacked on dim0]."""
    n_params = len(stages[0])
    out = []
    for i in range(n_params):
        out.append(jnp.stack([s[i] for s in stages]))
    return out


class PipelinedTrainStep:
    """Train step for (embed, blocks, head) models with pp (+dp/mp) sharding.

    blocks are partitioned uniformly into pp_degree stages; each stage applies
    blocks_per_stage blocks sequentially (weights stacked on a leading
    per-stage block dim, scanned inside the stage).
    """

    def __init__(self, embed_layer, blocks: Sequence, head_layer, loss_fn: Callable,
                 optimizer=None, mesh: Mesh | None = None, num_micro: int = 1,
                 remat: bool = True, seed: int = 0):
        self.mesh = mesh if mesh is not None else get_mesh()
        if self.mesh is None or "pp" not in self.mesh.shape:
            raise ValueError("PipelinedTrainStep requires a mesh with a 'pp' axis")
        self.S = int(self.mesh.shape["pp"])
        if len(blocks) % self.S != 0:
            raise ValueError(f"{len(blocks)} blocks not divisible by pp={self.S}")
        self.blocks_per_stage = len(blocks) // self.S
        self.M = num_micro
        self.embed = embed_layer
        self.blocks = list(blocks)
        self.head = head_layer
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.remat = remat
        self._key = jax.random.key(seed)
        self._step_i = 0

        mesh = self.mesh
        self._dp_axes = tuple(a for a in ("dp", "sharding") if a in mesh.shape and mesh.shape[a] > 1)

        # ---- parameter pytrees ------------------------------------------------
        self._embed_params = embed_layer.parameters()
        self._head_params = head_layer.parameters()
        self._block_params = [b.parameters() for b in blocks]
        nb = len(self._block_params[0])
        for bp in self._block_params:
            assert len(bp) == nb, "pipeline blocks must be homogeneous"

        # stacked block params: [n_layers, ...] -> reshaped [S, bps, ...]
        stacked = []
        for i in range(nb):
            vals = [bp[i]._value for bp in self._block_params]
            arr = jnp.stack(vals).reshape((self.S, self.blocks_per_stage) + vals[0].shape)
            stacked.append(arr)

        # shardings: leading dim over 'pp', inner dims by the param's mp spec
        def block_spec(p):
            inner = _param_pspec(p, mesh)
            return PartitionSpec("pp", None, *inner)

        self._block_specs = [block_spec(p) for p in self._block_params[0]]
        self._stacked_blocks = [
            jax.device_put(a, NamedSharding(mesh, s))
            for a, s in zip(stacked, self._block_specs)
        ]
        self._embed_specs = [_param_pspec(p, mesh) for p in self._embed_params]
        self._head_specs = [_param_pspec(p, mesh) for p in self._head_params]
        self._embed_vals = [jax.device_put(p._value, NamedSharding(mesh, s))
                            for p, s in zip(self._embed_params, self._embed_specs)]
        self._head_vals = [jax.device_put(p._value, NamedSharding(mesh, s))
                           for p, s in zip(self._head_params, self._head_specs)]

        # optimizer state over the flat param list (embed + blocks-stacked + head)
        self._opt_states = None
        if optimizer is not None:
            self._opt_states = []
            for v in self._embed_vals + self._stacked_blocks + self._head_vals:
                holder = Tensor(v)
                st = optimizer._init_state(holder)
                # co-locate state with its (sharded) parameter
                st = {k: jax.device_put(s, v.sharding) for k, s in st.items()}
                self._opt_states.append(st)

        self._jitted = None

    # -- stage function (runs under shard_map: local shards, axes bound) -----
    def _stage_fn(self, stage_params_local, x, key):
        """Apply this rank's blocks_per_stage blocks to x."""
        counter = [0]

        def next_key():
            counter[0] += 1
            return jax.random.fold_in(key, counter[0])

        def one_block(h, layer_params):
            prev = fleet_rng._tls.active_key_fn
            fleet_rng._tls.active_key_fn = next_key
            try:
                out = functional_call(self.blocks[0], layer_params, (h,))
            finally:
                fleet_rng._tls.active_key_fn = prev
            return out._value if isinstance(out, Tensor) else out, None

        block_fn = one_block
        if self.remat:
            block_fn = jax.checkpoint(one_block)
        h, _ = jax.lax.scan(block_fn, x, stage_params_local)
        return h

    def _pipeline_loss(self, stacked_blocks_local, embed_out_mb, labels_mb, head_vals, key):
        """Runs per-rank inside shard_map. embed_out_mb: [M, mb, S_seq, H] local;
        labels_mb: [M, mb, S_seq]."""
        S = self.S
        M = self.M
        idx = jax.lax.axis_index("pp")
        # strip the leading local pp dim (size 1 per rank)
        stage_params = [a[0] for a in stacked_blocks_local]

        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, acc_loss, acc_cnt = carry
            mb_idx = t - idx
            inp = jnp.where(idx == 0,
                            embed_out_mb[jnp.clip(t, 0, M - 1)],
                            state)
            out = self._stage_fn(stage_params, inp, jax.random.fold_in(key, t))
            valid = (mb_idx >= 0) & (mb_idx < M) & (idx == S - 1)
            # head + loss (masked off except on last stage's valid ticks)
            head_out = functional_call(self.head, head_vals, (out,))
            hv = head_out._value if isinstance(head_out, Tensor) else head_out
            lab = labels_mb[jnp.clip(mb_idx, 0, M - 1)]
            loss_t = self.loss_fn(Tensor(hv), Tensor(lab))
            lval = loss_t._value if isinstance(loss_t, Tensor) else loss_t
            acc_loss = acc_loss + jnp.where(valid, lval, 0.0)
            acc_cnt = acc_cnt + jnp.where(valid, 1.0, 0.0)
            nxt = jax.lax.ppermute(out, "pp", perm)
            return (nxt, acc_loss, acc_cnt), None

        zero = jnp.zeros_like(embed_out_mb[0])
        (state, loss_sum, cnt), _ = jax.lax.scan(
            tick, (zero, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            jnp.arange(M + S - 1),
        )
        # sum over pp (only last rank nonzero) and average over dp shards
        loss = jax.lax.psum(loss_sum, "pp") / jnp.maximum(jax.lax.psum(cnt, "pp"), 1.0)
        if self._dp_axes:
            loss = jax.lax.pmean(loss, self._dp_axes)
        return loss

    # -- whole step -----------------------------------------------------------
    def _loss_of(self, embed_vals, stacked_blocks, head_vals, ids, labels, key):
        mesh = self.mesh
        # embedding outside the pipeline region (GSPMD-sharded over dp/mp)
        emb_out = functional_call(self.embed, embed_vals, (ids,))
        x = emb_out._value if isinstance(emb_out, Tensor) else emb_out
        B = x.shape[0]
        mb = B // self.M
        x_mb = x.reshape((self.M, mb) + x.shape[1:])
        lab_mb = labels.reshape((self.M, mb) + labels.shape[1:])

        dp = self._dp_axes
        data_spec = PartitionSpec(None, dp if dp else None)
        in_specs = (
            tuple(self._block_specs),
            PartitionSpec(None, dp if dp else None, *([None] * (x.ndim - 1))),
            PartitionSpec(None, dp if dp else None, *([None] * (labels.ndim - 1))),
            # head enters mp-sharded (vocab shard per mp rank) so the in-pipeline
            # ParallelCrossEntropy sees true local shards
            tuple(self._head_specs),
            PartitionSpec(),
        )
        try:
            from jax import shard_map

            fn = shard_map(self._pipeline_loss, mesh=mesh, in_specs=in_specs,
                           out_specs=PartitionSpec(), check_vma=False)
        except (ImportError, TypeError):  # older jax API
            from jax.experimental.shard_map import shard_map

            fn = shard_map(self._pipeline_loss, mesh=mesh, in_specs=in_specs,
                           out_specs=PartitionSpec(), check_rep=False)
        return fn(tuple(stacked_blocks), x_mb, lab_mb, tuple(head_vals), key)

    def _step_fn(self, embed_vals, stacked_blocks, head_vals, opt_states, ids, labels,
                 key, lr, step_i):
        def loss_fn(ev, sb, hv):
            return self._loss_of(ev, sb, hv, ids, labels, key)

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
            embed_vals, stacked_blocks, head_vals
        )
        g_embed, g_blocks, g_head = grads
        flat_p = list(embed_vals) + list(stacked_blocks) + list(head_vals)
        flat_g = list(g_embed) + list(g_blocks) + list(g_head)
        if self.optimizer is None:
            return loss, embed_vals, stacked_blocks, head_vals, opt_states
        new_p, new_s = [], []
        for pv, gv, st in zip(flat_p, flat_g, opt_states):
            if gv.dtype != pv.dtype:
                gv = gv.astype(pv.dtype)
            np_, ns_ = self.optimizer._update(pv, gv, st, lr, step_i)
            new_p.append(np_)
            new_s.append(ns_)
        ne = len(embed_vals)
        nb = len(stacked_blocks)
        return (loss, new_p[:ne], new_p[ne:ne + nb], new_p[ne + nb:], new_s)

    def __call__(self, ids, labels):
        if self._jitted is None:
            self._jitted = jax.jit(self._step_fn, donate_argnums=(0, 1, 2, 3))
        iv = ids._value if isinstance(ids, Tensor) else jnp.asarray(ids)
        lv = labels._value if isinstance(labels, Tensor) else jnp.asarray(labels)
        dp = self._dp_axes
        bspec = PartitionSpec(dp if dp else None)
        iv = jax.device_put(iv, NamedSharding(self.mesh, bspec))
        lv = jax.device_put(lv, NamedSharding(self.mesh, bspec))
        self._step_i += 1
        self._key, sub = jax.random.split(self._key)
        lr = jnp.asarray(self.optimizer.get_lr() if self.optimizer else 0.0, jnp.float32)
        out = self._jitted(self._embed_vals, self._stacked_blocks, self._head_vals,
                           self._opt_states, iv, lv, sub, lr,
                           jnp.asarray(self._step_i, jnp.int32))
        loss, self._embed_vals, self._stacked_blocks, self._head_vals, self._opt_states = out
        return Tensor(loss)

    def sync_params_to_model(self):
        for p, v in zip(self._embed_params, self._embed_vals):
            p._set_value(v)
        for p, v in zip(self._head_params, self._head_vals):
            p._set_value(v)
        for i, stacked in enumerate(self._stacked_blocks):
            flat = stacked.reshape((self.S * self.blocks_per_stage,) + stacked.shape[2:])
            for l, bp in enumerate(self._block_params):
                bp[i]._set_value(flat[l])
