"""Compiled pipeline parallelism: the whole schedule is ONE XLA program.

Reference analog: Fleet's 1F1B runtime (pipeline_parallel.py:459
forward_backward_pipeline) + batched p2p (p2p_communication.py:322) + the
static pipeline passes / FleetExecutor (SURVEY §2.1).

TPU-native design (the "pipelining via collective_permute" recipe of the
scaling book): inside `shard_map` over the "pp" mesh axis each rank holds ONE
stage's parameters (stacked pytree, leading dim = pp). A `lax.scan` streams
M microbatches through T = M + S - 1 ticks; activations hop to the next stage
with `lax.ppermute` over ICI. Differentiating through the scan gives the
reverse (backward) pipeline automatically — XLA schedules fwd/bwd ticks and
overlaps the permutes with compute, which is exactly the 1F1B overlap the
reference hand-codes with comm streams. Tensor parallelism composes: inside
shard_map the "mp" axis is bound, so the mpu layers' explicit collectives
(identity/psum pairs, mp_ops.py) activate with local shards.

Microbatch loss masking: each rank computes every tick, but only
(rank == S-1, valid mb) ticks contribute loss; invalid ticks are masked out.
The embedding/head run in-pipeline on the first/last stage's rank.
"""
from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.fleet import rng as fleet_rng
from paddle_tpu.distributed.mesh import get_mesh
from paddle_tpu.parallel.train_step import _param_pspec, functional_call

__all__ = ["PipelinedTrainStep"]

from paddle_tpu.distributed.mesh import shard_map_compat as _shard_map  # noqa: E402


def _stack_params(stages):
    """Stack homogeneous per-stage param lists: list[stage][param] -> list[param stacked on dim0]."""
    n_params = len(stages[0])
    out = []
    for i in range(n_params):
        out.append(jnp.stack([s[i] for s in stages]))
    return out


def _interleave_schedule(S: int, v: int, M: int):
    """Statically simulate the interleaved-VPP schedule (drain-first priority,
    reference PipelineParallelWithInterleave, pipeline_parallel.py:1010).

    The virtual ring has S*v positions; position p = chunk*S + rank. Each tick
    every rank processes at most ONE chunk (1/v of its layers) and the result
    hops to the next rank. Drain-first priority + inject-when-idle gives the
    Megatron bubble: T ≈ M*v + S - 1 chunk-ticks (vs (M + S - 1) full-stage
    ticks for 1F1B — the fill/drain bubble shrinks by ~v).

    Returns numpy int/bool arrays indexed [T, S]:
      proc_chunk, proc_valid, inject_mb (-1 = none), out_valid, out_mb,
      dst_chunk, dst_valid  (where the ppermuted activation lands next tick).
    """
    positions = {}  # p -> mb currently WAITING at p
    next_inject = 0
    # per-tick records
    proc_chunk, proc_valid, inject_mb, out_valid, out_mb = [], [], [], [], []
    exited = 0
    t = 0
    max_ticks = M * v + 2 * S * v + 4
    while exited < M and t < max_ticks:
        pc = [0] * S
        pv = [False] * S
        im = [-1] * S
        ov = [False] * S
        om = [0] * S
        moved = {}  # p_dst -> mb arriving at t+1
        busy = [False] * S
        # process in descending position order (drain-first); a rank takes the
        # furthest-along waiting activation whose destination station is free
        for p in sorted(positions.keys(), reverse=True):
            r = p % S
            if busy[r]:
                continue
            dst = p + 1
            if dst < S * v and (dst in positions or dst in moved):
                continue  # destination occupied and not vacating
            m = positions.pop(p)
            busy[r] = True
            pc[r] = p // S
            pv[r] = True
            if dst == S * v:
                ov[r] = True
                om[r] = m
                exited += 1
            else:
                moved[dst] = m
        # inject at rank 0 chunk 0 when idle and station 0 path free
        if (not busy[0]) and next_inject < M and 0 not in positions and 0 not in moved:
            m = next_inject
            next_inject += 1
            busy[0] = True
            pc[0] = 0
            pv[0] = True
            im[0] = m
            if S * v == 1:
                ov[0] = True
                om[0] = m
                exited += 1
            else:
                moved[1] = m
        for p, m in moved.items():
            assert p not in positions, f"station collision at p={p} t={t}"
            positions[p] = m
        proc_chunk.append(pc)
        proc_valid.append(pv)
        inject_mb.append(im)
        out_valid.append(ov)
        out_mb.append(om)
        t += 1
    assert exited == M, f"schedule did not drain: {exited}/{M} in {t} ticks"
    T = t
    proc_chunk = np.array(proc_chunk, np.int32)
    proc_valid = np.array(proc_valid, bool)
    inject_mb = np.array(inject_mb, np.int32)
    out_valid = np.array(out_valid, bool)
    out_mb = np.array(out_mb, np.int32)
    # destination bookkeeping: rank r receives what rank r-1 processed
    dst_chunk = np.zeros((T, S), np.int32)
    dst_valid = np.zeros((T, S), bool)
    for tt in range(T):
        for r in range(S):
            src = (r - 1) % S
            if proc_valid[tt, src] and not out_valid[tt, src]:
                dst_chunk[tt, r] = proc_chunk[tt, src] + (1 if r == 0 else 0)
                dst_valid[tt, r] = True
    return dict(T=T, proc_chunk=proc_chunk, proc_valid=proc_valid,
                inject_mb=inject_mb, out_valid=out_valid, out_mb=out_mb,
                dst_chunk=dst_chunk, dst_valid=dst_valid)


class PipelinedTrainStep:
    """Train step for (embed, blocks, head) models with pp (+dp/mp) sharding.

    blocks are partitioned uniformly into pp_degree stages; each stage applies
    blocks_per_stage blocks sequentially (weights stacked on a leading
    per-stage block dim, scanned inside the stage).
    """

    def __init__(self, embed_layer, blocks: Sequence, head_layer, loss_fn: Callable,
                 optimizer=None, mesh: Mesh | None = None, num_micro: int = 1,
                 remat: bool | str | None = True, seed: int = 0,
                 virtual_pp: int = 1, zero_axis: str | None = None,
                 fp8_policy: str | None = None):
        from paddle_tpu.amp.fp8 import normalize_fp8_policy
        from paddle_tpu.core.flags import flag
        from paddle_tpu.parallel.scan_layers import normalize_remat

        # remat: policy string (none|full|save_dots|save_nothing|
        # offload_residuals) applied PER SCANNED LAYER in each stage's chunk;
        # bool back-compat (True -> 'full'), None reads the remat_policy flag
        self.remat_policy = normalize_remat(
            flag("remat_policy") if remat is None else remat)
        self.remat = self.remat_policy != "none"
        # fp8_policy (none|matmuls|matmuls+head): the schedule stashes and
        # replays per-microbatch vjps, so the pipelined runtimes use the
        # STATELESS current-scaling fp8 variant (scales from the live
        # tensors each microbatch — no cross-step amax state to carry;
        # CompiledTrainStep is the delayed-scaling path)
        self.fp8_policy = normalize_fp8_policy(
            flag("fp8_policy") if fp8_policy is None else fp8_policy)
        self.mesh = mesh if mesh is not None else get_mesh()
        if self.mesh is None or "pp" not in self.mesh.shape:
            raise ValueError("PipelinedTrainStep requires a mesh with a 'pp' axis")
        self.S = int(self.mesh.shape["pp"])
        self.V = int(virtual_pp)
        if len(blocks) % (self.S * self.V) != 0:
            raise ValueError(
                f"{len(blocks)} blocks not divisible by pp*virtual_pp={self.S * self.V}")
        self.blocks_per_stage = len(blocks) // self.S
        self.M = num_micro
        self.embed = embed_layer
        self.blocks = list(blocks)
        self.head = head_layer
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._key = jax.random.key(seed)
        # resume parity: continue from a restored optimizer's step count
        from paddle_tpu.parallel.train_step import _innermost_opt

        self._step_i = (int(getattr(_innermost_opt(optimizer), "_step_count",
                                    0) or 0) if optimizer is not None else 0)
        self._sched = (_interleave_schedule(self.S, self.V, self.M)
                       if self.V > 1 else None)

        mesh = self.mesh
        self._dp_axes = tuple(a for a in ("dp", "sharding") if a in mesh.shape and mesh.shape[a] > 1)
        self._dp_axes0 = self._dp_axes
        self._jit_cache = {}
        # async feed/dispatch: pre-placed batches skip device_put; the
        # window bounds un-fetched steps in flight (train_step contract)
        from paddle_tpu.io.device_feed import DispatchWindow

        self._window = DispatchWindow()
        self._bshard_cache = {}
        self.h2d_transfers = 0

        # ---- parameter pytrees ------------------------------------------------
        self._embed_params = embed_layer.parameters()
        self._head_params = head_layer.parameters()
        self._block_params = [b.parameters() for b in blocks]
        nb = len(self._block_params[0])
        for bp in self._block_params:
            assert len(bp) == nb, "pipeline blocks must be homogeneous"

        # stacked block params: [n_layers, ...] -> [S, bps, ...] (1F1B) or
        # [S, V, bpc, ...] (interleaved: position p = chunk*S + rank holds
        # layers [p*bpc, (p+1)*bpc) — the Megatron virtual-stage layout)
        stacked = []
        bpc = len(blocks) // (self.S * self.V)
        for i in range(nb):
            vals = [bp[i]._value for bp in self._block_params]
            if self.V == 1:
                arr = jnp.stack(vals).reshape((self.S, self.blocks_per_stage) + vals[0].shape)
            else:
                arr = jnp.stack(vals).reshape((self.V, self.S, bpc) + vals[0].shape)
                arr = jnp.moveaxis(arr, 1, 0)  # -> [S, V, bpc, ...]
            stacked.append(arr)

        # ZeRO-3 per-stage sharding (composes with pp): each stage's block
        # params ALSO persist reduce-scattered over `zero_axis`; the stage
        # scan gathers block i+1's weights while block i computes and the
        # all_gather transpose (psum_scatter) reduce-scatters the grads
        self.zero_axis = None
        if zero_axis is not None and zero_axis not in mesh.shape:
            import warnings

            warnings.warn(
                f"zero_axis={zero_axis!r} is not a mesh axis "
                f"({tuple(mesh.shape)}); per-stage ZeRO sharding is OFF")
        if (zero_axis is not None and zero_axis in mesh.shape
                and mesh.shape[zero_axis] > 1):
            if self.V > 1:
                raise ValueError(
                    "zero_axis sharding is not supported with interleaved "
                    "virtual_pp yet; use virtual_pp=1 (1F1B)")
            if zero_axis not in self._dp_axes0:
                # the psum_scatter grad reduction (the all_gather transpose)
                # is only correct when the batch is sharded over the axis;
                # a replicated batch would silently scale dW by the shard
                # count (ZBH1 divides by it instead — its batch is always
                # replicated)
                raise ValueError(
                    f"zero_axis={zero_axis!r} must be a data axis the batch "
                    f"shards over ({self._dp_axes0 or 'none in this mesh'})")
            self.zero_axis = zero_axis

        # shardings: leading dim over 'pp', inner dims by the param's mp spec
        # (+ the zero_axis on the first free divisible weight dim)
        self._zero_dims = None

        def block_spec(p, i):
            inner = _param_pspec(p, mesh)
            if self.V != 1:
                return PartitionSpec("pp", None, None, *inner)
            dims = ["pp", None] + list(inner)
            dims += [None] * (2 + p.ndim - len(dims))
            if self.zero_axis is not None:
                flat = [a for e in dims if e for a in
                        (e if isinstance(e, tuple) else (e,))]
                if self.zero_axis not in flat:
                    for d in range(2, 2 + p.ndim):
                        if (dims[d] is None and p.shape[d - 2]
                                % mesh.shape[self.zero_axis] == 0):
                            dims[d] = self.zero_axis
                            # gather axis in the PER-BLOCK slice (pp + stage
                            # dims stripped before the stage scan runs)
                            self._zero_dims[i] = d - 2
                            break
            return PartitionSpec(*dims)

        if self.V == 1:
            self._zero_dims = [None] * len(self._block_params[0])
        self._block_specs = [block_spec(p, i)
                             for i, p in enumerate(self._block_params[0])]
        if self._zero_dims is None or all(d is None for d in self._zero_dims):
            if self.zero_axis is not None:
                import warnings

                warnings.warn(
                    f"zero_axis={self.zero_axis!r}: no block param dim "
                    f"divides the axis; per-stage params persist REPLICATED")
            self._zero_dims = None
            self.zero_axis = None
        self._stacked_blocks = [
            jax.device_put(a, NamedSharding(mesh, s))
            for a, s in zip(stacked, self._block_specs)
        ]
        self._embed_specs = [_param_pspec(p, mesh) for p in self._embed_params]
        self._head_specs = [_param_pspec(p, mesh) for p in self._head_params]
        self._embed_vals = [jax.device_put(p._value, NamedSharding(mesh, s))
                            for p, s in zip(self._embed_params, self._embed_specs)]
        self._head_vals = [jax.device_put(p._value, NamedSharding(mesh, s))
                           for p, s in zip(self._head_params, self._head_specs)]

        # optimizer state over the flat param list (embed + blocks-stacked + head)
        from paddle_tpu.parallel.train_step import init_opt_states

        self._opt_states = None
        if optimizer is not None:
            # resume path: a restored optimizer._state (elastic checkpoint /
            # set_state_dict) seeds the moments instead of zero re-init
            self._opt_states = init_opt_states(
                optimizer,
                self._embed_vals + self._stacked_blocks + self._head_vals,
                params=(self._embed_params
                        + [None] * len(self._stacked_blocks)
                        + self._head_params),
                block_params=self._block_params, stack=self._stack)

        self._jitted = None

    # -- stage function (runs under shard_map: local shards, axes bound) -----
    def _stage_fn(self, stage_params_local, x, key):
        """Apply this rank's blocks_per_stage blocks to x."""
        counter = [0]

        def next_key():
            counter[0] += 1
            return jax.random.fold_in(key, counter[0])

        def one_block(h, layer_params):
            prev = fleet_rng._tls.active_key_fn
            fleet_rng._tls.active_key_fn = next_key
            try:
                out = functional_call(self.blocks[0], layer_params, (h,))
            finally:
                fleet_rng._tls.active_key_fn = prev
            return out._value if isinstance(out, Tensor) else out, None

        from paddle_tpu.parallel.scan_layers import remat_wrap

        # selective remat per scanned layer: 'full' recomputes the block
        # interior (the old remat=True), 'save_dots' keeps matmul outputs,
        # 'offload_residuals' parks tagged residuals in pinned host memory
        block_fn = remat_wrap(one_block, self.remat_policy, in_scan=True)
        if self.zero_axis is None:
            h, _ = jax.lax.scan(block_fn, x, stage_params_local)
            return h

        # ZeRO-3 within the stage: block params arrive reduce-scattered over
        # zero_axis; double-buffered gather-ahead reconstructs block i+1's
        # weights while block i computes. Backward reduce-scatters the weight
        # grads automatically (psum_scatter is the all_gather transpose).
        def gather(vals):
            return [v if d is None
                    else jax.lax.all_gather(v, self.zero_axis, axis=d,
                                            tiled=True)
                    for v, d in zip(vals, self._zero_dims)]

        first = gather([a[0] for a in stage_params_local])
        # iteration i's xs slice carries block i+1's shards (tail wraps to 0)
        rolled = [jnp.roll(a, -1, axis=0) for a in stage_params_local]

        def body(carry, xs):
            h, cur = carry
            nxt = gather(list(xs))  # block i+1, overlaps block i's compute
            h2, _ = block_fn(h, cur)
            return (h2, nxt), None

        (h, _), _ = jax.lax.scan(body, (x, first), tuple(rolled))
        return h

    def _pipeline_loss(self, stacked_blocks_local, embed_out_mb, key,
                       extras_mb=None):
        """Runs per-rank inside shard_map. embed_out_mb: [M, mb, S_seq, H] local.

        The tick loop runs ONLY decoder blocks; finished microbatches are
        collected into a buffer and returned ([1, M, mb, ...] per rank, stacked
        over 'pp' outside) — the vocab head+loss run in a separate pp-sharded
        region (_head_loss_pp), so no rank ever computes a head it discards.

        extras_mb: optional dict of per-microbatch [M, mb, ...] batch
        metadata (segment_ids/position_ids of a packed batch). Each tick
        publishes the PROCESSED microbatch's slice (index t - rank, the mb
        this rank's stage is computing) through the segment context so
        segment-aware blocks pick it up — the activation wire format never
        changes, and blocks that ignore the context are untouched."""
        S = self.S
        M = self.M
        idx = jax.lax.axis_index("pp")
        # strip the leading local pp dim (size 1 per rank)
        stage_params = [a[0] for a in stacked_blocks_local]

        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            from contextlib import nullcontext

            from paddle_tpu.parallel.segments import segment_execution

            state, outbuf = carry
            mb_idx = t - idx
            inp = jnp.where(idx == 0,
                            embed_out_mb[jnp.clip(t, 0, M - 1)],
                            state)
            ctx = nullcontext()
            if extras_mb:
                j = jnp.clip(mb_idx, 0, M - 1)
                cur = {k: jax.lax.dynamic_index_in_dim(v, j, 0, keepdims=False)
                       for k, v in extras_mb.items()}
                ctx = segment_execution(cur.get("segment_ids"),
                                        cur.get("position_ids"))
            with ctx:
                out = self._stage_fn(stage_params, inp,
                                     jax.random.fold_in(key, t))
            # collect the microbatch exiting the last stage this tick
            valid = (mb_idx >= 0) & (mb_idx < M) & (idx == S - 1)
            j = jnp.clip(mb_idx, 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(outbuf, j, 0, keepdims=False)
            outbuf = jax.lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(valid, out, cur), j, 0)
            nxt = jax.lax.ppermute(out, "pp", perm)
            return (nxt, outbuf), None

        zero = jnp.zeros_like(embed_out_mb[0])
        outbuf0 = jnp.zeros_like(embed_out_mb)
        (state, outbuf), _ = jax.lax.scan(
            tick, (zero, outbuf0), jnp.arange(M + S - 1),
        )
        return outbuf[None]

    def _head_loss_pp(self, outbuf, labels_mb, head_vals):
        """Head + loss over the collected last-stage activations, as its own
        shard_map region with the MICROBATCH dim sharded over 'pp' (when
        M % S == 0): each pp rank computes the vocab matmul for M/S
        microbatches, so the head costs 1/S of the reference's last-stage-only
        design and never rides the pipeline critical path (VERDICT r2 weak #3:
        previously every rank computed all M heads and discarded S-1 of them).
        lax.map chunks per-microbatch to keep peak logits memory at one mb."""
        mesh = self.mesh
        dp = self._dp_axes
        lead = "pp" if self.M % self.S == 0 else None
        # joint head+loss fusion (chunked fused CE, no [tokens, vocab]
        # logits) when the head/loss pair opts in; None -> unfused path.
        # Resolved at trace time: flipping use_fused_head_loss after the
        # first step does not retrace.
        from paddle_tpu.parallel.fused_head import (fused_head_loss,
                                                    fused_head_spec)

        fspec = fused_head_spec(self.head, self.loss_fn)

        def body(out_loc, lab_loc, hv):
            from paddle_tpu.amp.fp8 import head_scope

            def per_mb(args):
                out_m, lab_m = args
                if fspec is not None:
                    # fused path: the fused-CE kernel reads the fp8 policy
                    # itself ('matmuls+head' quantizes the projection)
                    return fused_head_loss(self.head, hv, out_m, lab_m,
                                           fspec).astype(jnp.float32)
                with head_scope():
                    head_out = functional_call(self.head, hv,
                                               (Tensor(out_m),))
                o = head_out._value if isinstance(head_out, Tensor) else head_out
                loss_t = self.loss_fn(Tensor(o), Tensor(lab_m))
                lv = loss_t._value if isinstance(loss_t, Tensor) else loss_t
                return lv.astype(jnp.float32)

            lval = jnp.mean(jax.lax.map(per_mb, (out_loc, lab_loc)))
            # mean over pp slices of per-slice means == global mean (equal M/S
            # counts); when lead is None (replicated) this also scales the
            # transpose's pp-psum of head grads back to 1x.
            lval = jax.lax.pmean(lval, "pp")
            if dp:
                lval = jax.lax.pmean(lval, dp)
            return lval

        in_specs = (
            PartitionSpec(lead, dp if dp else None, *([None] * (outbuf.ndim - 2))),
            PartitionSpec(lead, dp if dp else None, *([None] * (labels_mb.ndim - 2))),
            tuple(self._head_specs),
        )
        fn = _shard_map(body, mesh, in_specs, PartitionSpec())
        return fn(outbuf, labels_mb, tuple(head_vals))

    def _pipeline_loss_vpp(self, stacked_blocks_local, embed_out_mb, key):
        """Interleaved-VPP schedule (reference pipeline_parallel.py:1010):
        each tick applies ONE chunk (1/V of this rank's layers) per rank and
        ppermutes the activation; the static schedule from
        _interleave_schedule drives slot/chunk selection. Fill+drain bubble is
        S-1 chunk-ticks instead of 1F1B's (S-1)*V (total T = M*V + S - 1)."""
        S, M = self.S, self.M
        idx = jax.lax.axis_index("pp")
        chunk_params = [a[0] for a in stacked_blocks_local]  # [V, bpc, ...]
        sch = self._sched
        proc_chunk = jnp.asarray(sch["proc_chunk"])
        proc_valid = jnp.asarray(sch["proc_valid"])
        inject_mb = jnp.asarray(sch["inject_mb"])
        out_valid = jnp.asarray(sch["out_valid"])
        out_mb = jnp.asarray(sch["out_mb"])
        dst_chunk = jnp.asarray(sch["dst_chunk"])
        dst_valid = jnp.asarray(sch["dst_valid"])
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            buf, outbuf = carry  # buf: [V, mb, seq, H] wrap-k slots
            k = proc_chunk[t, idx]
            valid = proc_valid[t, idx]
            inj = inject_mb[t, idx]
            x_slot = jax.lax.dynamic_index_in_dim(buf, k, 0, keepdims=False)
            x_inj = embed_out_mb[jnp.clip(inj, 0, M - 1)]
            x = jnp.where(inj >= 0, x_inj, x_slot)
            params_k = [jax.lax.dynamic_index_in_dim(a, k, 0, keepdims=False)
                        for a in chunk_params]
            y = self._stage_fn(params_k, x, jax.random.fold_in(key, t))
            y = jnp.where(valid, y, x)
            # exit collection (chunk V-1 finishing on rank S-1)
            ov = out_valid[t, idx]
            om = jnp.clip(out_mb[t, idx], 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(outbuf, om, 0, keepdims=False)
            outbuf = jax.lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(ov, y, cur), om, 0)
            # hop to the next rank; store into the destination wrap slot
            y_recv = jax.lax.ppermute(y, "pp", perm)
            ds = dst_chunk[t, idx]
            dv = dst_valid[t, idx]
            cur2 = jax.lax.dynamic_index_in_dim(buf, ds, 0, keepdims=False)
            buf = jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(dv, y_recv, cur2), ds, 0)
            return (buf, outbuf), None

        buf0 = jnp.zeros((self.V,) + embed_out_mb.shape[1:], embed_out_mb.dtype)
        outbuf0 = jnp.zeros_like(embed_out_mb)
        (_, outbuf), _ = jax.lax.scan(
            tick, (buf0, outbuf0), jnp.arange(sch["T"]),
        )
        return outbuf[None]

    # -- whole step -----------------------------------------------------------
    def _loss_of(self, embed_vals, stacked_blocks, head_vals, ids, labels, key,
                 extras=None):
        mesh = self.mesh
        # embedding outside the pipeline region (GSPMD-sharded over dp/mp)
        emb_out = functional_call(self.embed, embed_vals, (ids,))
        x = emb_out._value if isinstance(emb_out, Tensor) else emb_out
        B = x.shape[0]
        mb = B // self.M
        x_mb = x.reshape((self.M, mb) + x.shape[1:])
        lab_mb = labels.reshape((self.M, mb) + labels.shape[1:])

        dp = self._dp_axes
        in_specs = (
            tuple(self._block_specs),
            PartitionSpec(None, dp if dp else None, *([None] * (x.ndim - 1))),
            PartitionSpec(),
        )
        # per-rank outbuf slices stacked over 'pp' -> [S, M, mb, ...] global
        out_spec = PartitionSpec("pp", None, dp if dp else None,
                                 *([None] * (x.ndim - 1)))
        if extras:
            # packed-batch metadata, microbatched like labels and replicated
            # over 'pp' (every stage needs the mb it currently processes)
            ex_mb = {k: v.reshape((self.M, mb) + v.shape[1:])
                     for k, v in extras.items()}
            in_specs = in_specs + (
                PartitionSpec(None, dp if dp else None, None), )
            fn = _shard_map(
                lambda sb, xm, k, ex: self._pipeline_loss(sb, xm, k, ex),
                mesh, in_specs, out_spec)
            stacked_out = fn(tuple(stacked_blocks), x_mb, key, ex_mb)
        else:
            body = (self._pipeline_loss if self.V == 1
                    else self._pipeline_loss_vpp)
            fn = _shard_map(body, mesh, in_specs, out_spec)
            stacked_out = fn(tuple(stacked_blocks), x_mb, key)
        # only the last stage's buffer is real; head+loss run pp-sharded
        return self._head_loss_pp(stacked_out[self.S - 1], lab_mb, head_vals)

    def _step_fn(self, embed_vals, stacked_blocks, head_vals, opt_states, ids, labels,
                 key, lr, step_i, extras=None):
        from paddle_tpu.amp.fp8 import fp8_execution

        def loss_fn(ev, sb, hv):
            # stateless (current-scaling) fp8 session active for the whole
            # pipeline trace; the head region gates itself via head_scope
            with fp8_execution(self.fp8_policy):
                return self._loss_of(ev, sb, hv, ids, labels, key, extras)

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
            embed_vals, stacked_blocks, head_vals
        )
        g_embed, g_blocks, g_head = grads
        flat_p = list(embed_vals) + list(stacked_blocks) + list(head_vals)
        flat_g = list(g_embed) + list(g_blocks) + list(g_head)
        if self.optimizer is None:
            return loss, embed_vals, stacked_blocks, head_vals, opt_states
        from paddle_tpu.parallel.train_step import apply_optimizer_update

        new_p, new_s = apply_optimizer_update(
            self.optimizer, flat_p, flat_g, opt_states, lr, step_i)
        ne = len(embed_vals)
        nb = len(stacked_blocks)
        return (loss, new_p[:ne], new_p[ne:ne + nb], new_p[ne + nb:], new_s)

    def __call__(self, ids, labels, *, segment_ids=None, position_ids=None):
        """ids/labels (+ optional KEYWORD-ONLY packed-batch
        segment_ids/position_ids, all
        [M*mb, seq]-leading): the extra leaves are microbatched alongside
        labels and delivered to each stage's blocks through the segment
        context — same jit cache, no per-step retracing (the cache key is
        the dp layout; the extras' presence is part of the traced structure
        and stable across a run)."""
        extras = {k: v for k, v in (("segment_ids", segment_ids),
                                    ("position_ids", position_ids))
                  if v is not None}
        if extras and self.V > 1:
            raise ValueError(
                "interleaved virtual-pp does not support packed-batch "
                "segment/position ids yet; use virtual_pp=1 (1F1B)")
        iv = ids._value if isinstance(ids, Tensor) else jnp.asarray(ids)
        lv = labels._value if isinstance(labels, Tensor) else jnp.asarray(labels)
        extras = {k: (v._value if isinstance(v, Tensor) else jnp.asarray(v))
                  for k, v in extras.items()}
        # per-batch: replicate data when microbatch rows don't divide the data
        # axes (e.g. a trailing partial batch) without disabling dp for good
        eff_dp = self._dp_axes0
        if eff_dp:
            div = int(np.prod([self.mesh.shape[a] for a in eff_dp]))
            if iv.shape[0] % self.M or (iv.shape[0] // self.M) % div:
                if self.zero_axis is not None:
                    # replicating the batch would double-count the
                    # psum_scatter'd weight grads of the sharded blocks
                    raise ValueError(
                        f"zero_axis={self.zero_axis!r} requires microbatch "
                        f"rows divisible by the data axes "
                        f"{eff_dp} x num_micro={self.M}; got batch "
                        f"{iv.shape[0]}")
                eff_dp = ()
        cache_key = (eff_dp, tuple(sorted(extras)))
        self._dp_axes = eff_dp
        self._jitted = self._jit_cache.get(cache_key)
        if self._jitted is None:
            self._jitted = jax.jit(self._step_fn, donate_argnums=(0, 1, 2, 3))
            self._jit_cache[cache_key] = self._jitted
        dp = self._dp_axes
        bshard = self._bshard_cache.get(dp)
        if bshard is None:
            bshard = NamedSharding(self.mesh, PartitionSpec(dp if dp else None))
            self._bshard_cache[dp] = bshard

        def place(v):
            if (isinstance(v, jax.Array) and getattr(v, "committed", False)
                    and v.sharding == bshard):
                return v  # pre-placed (DeviceFeeder) fast path
            self.h2d_transfers += 1
            return jax.device_put(v, bshard)

        iv, lv = place(iv), place(lv)
        extras = {k: place(v) for k, v in extras.items()} or None
        self._step_i += 1
        self._key, sub = jax.random.split(self._key)
        lr = jnp.asarray(self.optimizer.get_lr() if self.optimizer else 0.0, jnp.float32)
        out = self._jitted(self._embed_vals, self._stacked_blocks, self._head_vals,
                           self._opt_states, iv, lv, sub, lr,
                           jnp.asarray(self._step_i, jnp.int32), extras)
        loss, self._embed_vals, self._stacked_blocks, self._head_vals, self._opt_states = out
        self._window.admit(loss)  # bound async run-ahead (~2 steps in flight)
        return Tensor(loss)

    @property
    def batch_spec(self):
        """Input layout for DeviceFeeder: batch dim over the data axes."""
        return PartitionSpec(self._dp_axes0 if self._dp_axes0 else None)

    def step_async(self, ids, labels, *, segment_ids=None, position_ids=None):
        """Dispatch one step, return a deferred-read LossFuture."""
        from paddle_tpu.io.device_feed import LossFuture

        return LossFuture(self(ids, labels, segment_ids=segment_ids,
                               position_ids=position_ids))

    def drain(self):
        self._window.drain()

    def _unstack(self, arr):
        """[S, bps, ...] (or [S, V, bpc, ...]) -> [n_layers, ...] in layer
        order — the inverse of the __init__ stacking."""
        if self.V == 1:
            return arr.reshape((self.S * self.blocks_per_stage,) + arr.shape[2:])
        # [S, V, bpc, ...] -> layer l = position*bpc + i, position = c*S + r
        return jnp.moveaxis(arr, 1, 0).reshape(
            (self.S * self.blocks_per_stage,) + arr.shape[3:])

    def _stack(self, vals):
        """[n_layers] per-layer arrays -> the __init__ stacked block layout
        (the inverse of `_unstack`; resumed optimizer moments go through
        here)."""
        bpc = (self.S * self.blocks_per_stage) // (self.S * self.V)
        arr = jnp.stack(list(vals))
        if self.V == 1:
            return arr.reshape((self.S, self.blocks_per_stage)
                               + arr.shape[1:])
        arr = arr.reshape((self.V, self.S, bpc) + arr.shape[1:])
        return jnp.moveaxis(arr, 1, 0)

    def sync_params_to_model(self):
        for p, v in zip(self._embed_params, self._embed_vals):
            p._set_value(v)
        for p, v in zip(self._head_params, self._head_vals):
            p._set_value(v)
        for i, stacked in enumerate(self._stacked_blocks):
            flat = self._unstack(stacked)
            for l, bp in enumerate(self._block_params):
                bp[i]._set_value(flat[l])

    def sync_states_to_optimizer(self):
        """Checkpoint parity (see train_step.sync_pipeline_states_to_optimizer)."""
        if self.optimizer is None or self._opt_states is None:
            return
        from paddle_tpu.parallel.train_step import (
            sync_pipeline_states_to_optimizer)

        sync_pipeline_states_to_optimizer(
            self.optimizer, self._opt_states, self._embed_params,
            self._head_params, self._block_params, self._unstack,
            self._step_i)
