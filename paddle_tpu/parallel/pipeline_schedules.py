"""Pipeline schedule generators: FThenB, 1F1B, interleaved, ZB-H1.

Reference parity: the static pipeline scheduler passes —
`pipeline_fthenb.py`, `pipeline_1f1b.py`, `pipeline_zero_bubble.py` under
python/paddle/distributed/passes/pipeline_scheduler_pass/ — which emit
per-rank instruction lists of {FORWARD, BACKWARD, (B, W)} jobs per
microbatch.

TPU-native role: the compiled pipeline (parallel/pipeline.py) runs the
1F1B/VPP dataflow as ONE differentiated scan — XLA schedules fwd/bwd ticks.
These generators produce the explicit per-tick tables for (a) schedule
analysis/validation (bubble + peak-activation accounting, used by the tests
and the auto-tuner) and (b) driving manually-scheduled execution where the
B/W split matters (ZB-H1 fills the 1F1B drain bubble with weight-grad work,
which has no data dependence on downstream stages).

Each generator returns a dict:
  ticks: list[list[(op, mb, chunk)]] indexed [t][rank]; op in
         {"F", "B", "W", None} (for 1F1B/FThenB, "B" includes W).
  bubble_frac(rank): fraction of idle (None) ticks.
  peak_activations(rank): max number of microbatches whose forward
         residuals are live at once on that rank.
All schedules are validated by `check_schedule` for data-dependency order:
F(mb) on rank r needs F(mb) on r-1 done; B(mb) on r needs B(mb) on r+1 and
F(mb) on r; W(mb) on r needs B(mb) on r.
"""
from __future__ import annotations

__all__ = ["fthenb_schedule", "one_f_one_b_schedule", "zb_h1_schedule",
           "check_schedule", "bubble_fraction", "peak_activations"]


def _empty(T, S):
    return [[None for _ in range(S)] for _ in range(T)]


def fthenb_schedule(S: int, M: int):
    """All forwards, then all backwards (reference pipeline_fthenb.py).
    Simple and bubble-equal to 1F1B, but every rank holds ALL M microbatch
    activations at the forward peak."""
    ticks = []
    T_f = M + S - 1
    for t in range(T_f):
        row = [None] * S
        for r in range(S):
            mb = t - r
            if 0 <= mb < M:
                row[r] = ("F", mb, 0)
        ticks.append(row)
    for t in range(M + S - 1):
        row = [None] * S
        for r in range(S):
            mb = t - (S - 1 - r)
            if 0 <= mb < M:
                row[r] = ("B", mb, 0)
        ticks.append(row)
    return {"name": "FThenB", "S": S, "M": M, "ticks": ticks}


def one_f_one_b_schedule(S: int, M: int):
    """1F1B (reference pipeline_1f1b.py / PipelineParallel:459): each rank
    runs at most S in-flight forwards before alternating F/B steady state.
    Backward costs one tick here (B includes W), so a backward tick on rank r
    for mb m is scheduled only after rank r+1 finished B(m)."""
    # simulate per-rank queues on a shared tick clock
    ticks = []
    f_done = [[-1] * M for _ in range(S)]   # tick when F(mb) finished on r
    b_done = [[-1] * M for _ in range(S)]
    next_f = [0] * S
    next_b = [0] * S
    warmup = [min(S - r, M) for r in range(S)]  # in-flight cap per rank
    t = 0
    while any(next_b[r] < M for r in range(S)) and t < 4 * (M + S) + 8:
        row = [None] * S
        for r in range(S):
            mb_b = next_b[r]
            can_b = (mb_b < M and f_done[r][mb_b] >= 0 and f_done[r][mb_b] < t
                     and (r == S - 1 or (b_done[r + 1][mb_b] >= 0
                                         and b_done[r + 1][mb_b] < t)))
            in_flight = next_f[r] - next_b[r]
            mb_f = next_f[r]
            # the in-flight cap IS 1F1B's memory bound: idle rather than run
            # an (S+1)-th forward
            can_f = (mb_f < M and in_flight < warmup[r]
                     and (r == 0 or (f_done[r - 1][mb_f] >= 0
                                     and f_done[r - 1][mb_f] < t)))
            # steady state: prefer B once warmup forwards are in flight
            if can_b and (in_flight >= warmup[r] or not can_f):
                row[r] = ("B", mb_b, 0)
                b_done[r][mb_b] = t
                next_b[r] += 1
            elif can_f:
                row[r] = ("F", mb_f, 0)
                f_done[r][mb_f] = t
                next_f[r] += 1
        ticks.append(row)
        t += 1
    return {"name": "1F1B", "S": S, "M": M, "ticks": ticks}


def zb_h1_schedule(S: int, M: int):
    """ZB-H1 (reference pipeline_zero_bubble.py, Qi et al. 2023): backward
    splits into B (activation grad, on the critical path) and W (weight
    grad, no downstream dependence). W jobs fill the drain bubble, so with
    F=B=W=1 tick the steady bubble shrinks toward (S-1)/3 of 1F1B's."""
    ticks = []
    f_done = [[-1] * M for _ in range(S)]
    b_done = [[-1] * M for _ in range(S)]
    w_done = [[-1] * M for _ in range(S)]
    next_f = [0] * S
    next_b = [0] * S
    next_w = [0] * S
    warmup = [min(S - r, M) for r in range(S)]
    t = 0
    while any(next_w[r] < M for r in range(S)) and t < 6 * (M + S) + 12:
        row = [None] * S
        for r in range(S):
            mb_b = next_b[r]
            can_b = (mb_b < M and 0 <= f_done[r][mb_b] < t
                     and (r == S - 1 or 0 <= b_done[r + 1][mb_b] < t))
            in_flight = next_f[r] - next_b[r]
            mb_f = next_f[r]
            can_f = (mb_f < M and in_flight < warmup[r]
                     and (r == 0 or 0 <= f_done[r - 1][mb_f] < t))
            mb_w = next_w[r]
            can_w = mb_w < M and 0 <= b_done[r][mb_w] < t
            # priority: B when enough in flight (frees activations) > F > W
            # (W is bubble filler — it has no downstream consumer)
            if can_b and (in_flight >= warmup[r] or not can_f):
                row[r] = ("B", mb_b, 0)
                b_done[r][mb_b] = t
                next_b[r] += 1
            elif can_f:
                row[r] = ("F", mb_f, 0)
                f_done[r][mb_f] = t
                next_f[r] += 1
            elif can_w:
                row[r] = ("W", mb_w, 0)
                w_done[r][mb_w] = t
                next_w[r] += 1
        ticks.append(row)
        t += 1
    return {"name": "ZB-H1", "S": S, "M": M, "ticks": ticks}


def check_schedule(sched) -> None:
    """Validate data-dependency order; raises AssertionError on violation."""
    S, M, ticks = sched["S"], sched["M"], sched["ticks"]
    f_done = [[-1] * M for _ in range(S)]
    b_done = [[-1] * M for _ in range(S)]
    w_done = [[-1] * M for _ in range(S)]
    for t, row in enumerate(ticks):
        for r, job in enumerate(row):
            if job is None:
                continue
            op, mb, _ = job
            if op == "F":
                assert f_done[r][mb] == -1, f"duplicate F({mb}) on rank {r}"
                assert r == 0 or 0 <= f_done[r - 1][mb] < t, \
                    f"F({mb}) on {r} before upstream F at t={t}"
                f_done[r][mb] = t
            elif op == "B":
                assert 0 <= f_done[r][mb] < t, f"B({mb}) before F on {r}"
                assert r == S - 1 or 0 <= b_done[r + 1][mb] < t, \
                    f"B({mb}) on {r} before downstream B at t={t}"
                assert b_done[r][mb] == -1
                b_done[r][mb] = t
            elif op == "W":
                assert 0 <= b_done[r][mb] < t, f"W({mb}) before B on {r}"
                w_done[r][mb] = t
    has_w = any(job is not None and job[0] == "W"
                for row in ticks for job in row)
    for r in range(S):
        for m in range(M):
            assert f_done[r][m] >= 0 and b_done[r][m] >= 0, \
                f"missing F/B for mb {m} on rank {r}"
            if has_w:
                assert w_done[r][m] >= 0, f"missing W for mb {m} on rank {r}"


def bubble_fraction(sched, rank=None) -> float:
    """Idle-tick fraction (averaged over ranks unless one is given)."""
    ticks, S = sched["ticks"], sched["S"]
    ranks = range(S) if rank is None else [rank]
    idle = total = 0
    for r in ranks:
        for row in ticks:
            total += 1
            if row[r] is None:
                idle += 1
    return idle / max(total, 1)


def peak_activations(sched, rank=0) -> int:
    """Max microbatches whose forward residuals are live on `rank` (freed
    when the rank finishes the job that consumes them: B for F-residuals)."""
    live = set()
    peak = 0
    for row in sched["ticks"]:
        job = row[rank]
        if job is not None:
            op, mb, _ = job
            if op == "F":
                live.add(mb)
            elif op == "B":
                live.discard(mb)
            peak = max(peak, len(live))
    return peak
