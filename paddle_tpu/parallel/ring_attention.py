"""Ring attention: exact attention over a sequence sharded across chips.

Reference analog: the reference snapshot covers long context with Megatron-SP
+ the 'sep' mesh axis + flash attention (SURVEY §5 'Long-context'); it has NO
ring attention — this is capability headroom over the reference, required by
the north star's long-context mandate.

TPU-native design: inside shard_map over the "sep" axis each rank holds a
sequence shard of Q/K/V. K/V blocks rotate around the ring with
`lax.ppermute` over ICI while each rank accumulates its Q shard's attention
with streaming-softmax merges (m, l, acc). sep_size steps fully overlap
compute with the neighbor exchange (XLA pipelines the permute). Causal
masking uses global positions, so ranks skip no work but mask exactly.
Differentiable end-to-end (grad rides the ppermute transposes = reverse ring).
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

__all__ = ["ring_attention", "SEP_AXIS"]

SEP_AXIS = "sep"
_NEG_INF = -1e30


def _block_attn(q, k, v, qpos, kpos, scale, causal, q_chunk=512):
    """One Q-shard x K-block attention with stats. q:[B,Sq,H,D] k/v:[B,Sk,H,D].
    Returns (acc [B,Sq,H,D] f32 unnormalized, m [B,Sq,H,1], l [B,Sq,H,1]).
    Q is processed in chunks so peak score memory is O(q_chunk * Sk), not
    O(Sq * Sk) — the flash-style tiling, kept in jnp so the ring stays
    differentiable end-to-end."""
    qh = q.astype(jnp.float32)
    kh = k.astype(jnp.float32)
    vh = v.astype(jnp.float32)
    sq = qh.shape[1]
    chunk = min(q_chunk, sq)

    @jax.checkpoint
    def one_chunk(args):
        qc, qp = args  # [B, C, H, D], [C]
        s = jnp.einsum("bqhd,bkhd->bhqk", qc, kh) * scale
        if causal:
            mask = qp[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m = jnp.max(s, axis=-1)  # [B,H,C]
        p = jnp.exp(s - m[..., None])
        l = jnp.sum(p, axis=-1)
        acc = jnp.einsum("bhqk,bkhd->bqhd", p, vh)
        m = jnp.transpose(m, (0, 2, 1))[..., None]
        l = jnp.transpose(l, (0, 2, 1))[..., None]
        return acc, m, l

    if sq == chunk:
        return one_chunk((qh, qpos))
    # ceil-division tiling: Q rows are independent, so the remainder tile is
    # zero-padded and sliced off after (no divisor hunting — a prime shard
    # length must not degenerate to chunk=1). one_chunk is rematerialized so
    # the O(chunk * Sk) score bound holds in the BACKWARD pass too (lax.map
    # would otherwise stack every chunk's softmax residuals).
    nc = -(-sq // chunk)
    pad = nc * chunk - sq
    if pad:
        qh = jnp.pad(qh, ((0, 0), (0, pad), (0, 0), (0, 0)))
        qpos = jnp.concatenate([qpos, jnp.full((pad,), qpos[-1], qpos.dtype)])
    qs = qh.reshape(qh.shape[0], nc, chunk, *qh.shape[2:]).swapaxes(0, 1)
    qps = qpos.reshape(nc, chunk)
    accs, ms, ls = jax.lax.map(one_chunk, (qs, qps))

    def join(t):
        full = t.swapaxes(0, 1).reshape(t.shape[1], nc * chunk, *t.shape[3:])
        return full[:, :sq]

    return join(accs), join(ms), join(ls)


def ring_attention(q, k, v, axis_name: str = SEP_AXIS, causal: bool = True,
                   scale: float | None = None):
    """Exact attention for seq-sharded q,k,v: [B, S_local, H, D] per rank.
    Must be called inside shard_map with `axis_name` bound."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # static ring size: jax.lax.axis_size does not exist on this jax; the
    # python-int size feeds the static perm list, so read the bound axis env
    # (or fall back to the global mesh shape, which binds the shard_map
    # axes). An unresolvable axis must raise — silently defaulting to a
    # 1-rank ring would skip every neighbor exchange and corrupt attention.
    try:
        from jax._src.core import get_axis_env

        n = int(get_axis_env().axis_sizes[axis_name])
    except Exception:
        from paddle_tpu.distributed.mesh import get_mesh

        mesh = get_mesh()
        if mesh is None or axis_name not in mesh.shape:
            raise ValueError(
                f"ring_attention: axis {axis_name!r} is not bound (call "
                f"inside shard_map over a mesh carrying it)")
        n = int(mesh.shape[axis_name])
    idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    qpos = idx * s_local + jnp.arange(s_local)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, r):
        kk, vv, m, l, acc = carry

        def compute(_):
            src = (idx - r) % n  # which rank's block we currently hold
            kpos = src * s_local + jnp.arange(s_local)
            a_j, m_j, l_j = _block_attn(q, kk, vv, qpos, kpos, scale, causal)
            m_new = jnp.maximum(m, m_j)
            c_old = jnp.exp(m - m_new)
            c_new = jnp.exp(m_j - m_new)
            return (l * c_old + l_j * c_new, acc * c_old + a_j * c_new, m_new)

        if causal:
            # a K block strictly in this Q shard's future contributes
            # nothing: skip its matmuls entirely (roughly halves ring FLOPs)
            src = (idx - r) % n
            l, acc, m = jax.lax.cond(
                src > idx, lambda _: (l, acc, m), compute, None)
        else:
            l, acc, m = compute(None)
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        return (kk, vv, m, l, acc), None

    b, s_, h, d = q.shape
    m0 = jnp.full((b, s_, h, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, s_, h, 1), jnp.float32)
    acc0 = jnp.zeros((b, s_, h, d), jnp.float32)
    (kk, vv, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, acc0), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)
