"""Scan-over-layers compilation + policy-based selective rematerialization.

Reference analog: the recompute pass / `recompute_interval` knob of the
reference's hybrid-parallel stack (fleet recompute, SURVEY §2.1) — but
TPU-native, the T5X/MaxText way:

* **Scan-over-layers.** A homogeneous decoder stack (N identical layers) is
  executed as ONE `jax.lax.scan` over the layer parameters stacked along a
  leading layer axis, so the traced program contains the layer body once and
  HLO size / XLA compile time are O(1) in depth instead of O(N).
* **Selective remat policies.** The all-or-nothing `remat: bool` knob becomes
  a policy string applied PER LAYER via `jax.checkpoint` +
  `jax.checkpoint_policies`:

    - ``none``              no rematerialization (save everything XLA keeps)
    - ``full``              `jax.checkpoint` default: save only layer
                            boundaries, recompute the layer interior
    - ``save_nothing``      explicit `nothing_saveable` (alias of ``full``'s
                            default policy, spelled out)
    - ``save_dots``         `dots_with_no_batch_dims_saveable`: keep matmul
                            outputs, recompute the cheap elementwise tail
    - ``offload_residuals`` residual-stream activations (tagged
                            `checkpoint_name(..., "residual")` by the layer)
                            are offloaded to pinned host memory via
                            `save_and_offload_only_these_names` when the
                            backend has one (`host_memory_supported()`),
                            else saved on device (`save_only_these_names`)

  Because the policy wraps each layer (or the scan body), the embed / fused
  LM-head / CE segment is NEVER inside a remat region: the fused head is
  computed exactly once even under ``full``.

Cooperation protocol (how a compiled step talks to a model):

* A model that can apply per-layer remat itself sets
  ``layer_remat_capable = True`` and reads :func:`current_layer_ctx` in its
  forward. `CompiledTrainStep` then delivers the policy via
  :func:`layer_execution` instead of wrapping the whole loss in
  `jax.checkpoint` (the legacy behavior, kept for non-cooperating models).
* A model whose homogeneous stack can be scanned exposes ``scan_group()``
  returning the list of identical layers. `CompiledTrainStep(scan_layers=
  True)` stacks each layer parameter across the group OUTSIDE the program
  (one `[L, ...]` jit input per parameter) and delivers the stacked arrays
  through the same context; the model consumes them with
  :func:`scan_layer_stack`.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Callable, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "REMAT_POLICIES", "normalize_remat", "remat_wrap", "layer_execution",
    "current_layer_ctx", "LayerExecContext", "stack_layer_vals",
    "scan_layer_stack", "unrolled_layer_call",
]

REMAT_POLICIES = ("none", "full", "save_dots", "save_nothing",
                  "offload_residuals")

# checkpoint_name tag the decoder layers put on their residual stream; the
# offload_residuals policy keys on it
RESIDUAL_TAG = "residual"


def normalize_remat(remat) -> str:
    """Map the legacy bool knob onto the policy namespace.

    True -> 'full' (the old whole-graph remat semantics, now applied per
    layer for cooperating models), False/None -> 'none'; policy strings pass
    through validated.
    """
    if remat is None or remat is False:
        return "none"
    if remat is True:
        return "full"
    policy = str(remat)
    if policy not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat policy {policy!r}; expected one of "
            f"{'|'.join(REMAT_POLICIES)} (or a bool)")
    return policy


def _offload_policy():
    from paddle_tpu.parallel.train_step import host_memory_supported

    if host_memory_supported():
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=[RESIDUAL_TAG],
            offload_src="device", offload_dst="pinned_host")
    # no pinned-host space (CPU test backend): degrade to device-saved names,
    # preserving the recompute structure (and numerics) of the offload policy
    return jax.checkpoint_policies.save_only_these_names(RESIDUAL_TAG)


def remat_wrap(fn: Callable, policy: str, in_scan: bool = False) -> Callable:
    """Wrap `fn` (a pure jax function) in `jax.checkpoint` per `policy`.

    `in_scan=True` relaxes `prevent_cse` (safe and faster under
    `lax.scan`/`while`, per the jax.checkpoint docs).
    """
    policy = normalize_remat(policy)
    if policy == "none":
        return fn
    kw = dict(prevent_cse=not in_scan)
    if policy == "save_nothing":
        kw["policy"] = jax.checkpoint_policies.nothing_saveable
    elif policy == "save_dots":
        kw["policy"] = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    elif policy == "offload_residuals":
        kw["policy"] = _offload_policy()
    # 'full': jax.checkpoint's default (save only the wrapped fn's inputs)
    return jax.checkpoint(fn, **kw)


class LayerExecContext:
    """What a compiled step asks of a cooperating model's layer stack."""

    __slots__ = ("policy", "stacked")

    def __init__(self, policy: str = "none", stacked=None):
        self.policy = policy
        # stacked: per-parameter [L, ...] arrays for the model's scan_group()
        # (stacked OUTSIDE the traced program), or None when the model should
        # use its own (bound) per-layer parameters
        self.stacked = stacked


class _CtxTLS(threading.local):
    def __init__(self):
        self.ctx = None


_tls = _CtxTLS()


def current_layer_ctx() -> LayerExecContext | None:
    return _tls.ctx


@contextmanager
def layer_execution(policy: str = "none", stacked=None):
    prev = _tls.ctx
    _tls.ctx = LayerExecContext(policy, stacked)
    try:
        yield _tls.ctx
    finally:
        _tls.ctx = prev


def stack_layer_vals(per_layer_vals: Sequence[Sequence]) -> list:
    """list[L][P] parameter values -> list[P] arrays stacked on a new leading
    layer axis (the MaxText/T5X scanned-weights layout)."""
    n = len(per_layer_vals[0])
    for lp in per_layer_vals:
        if len(lp) != n:
            raise ValueError("scan group layers are not homogeneous")
    return [jnp.stack([lp[j] for lp in per_layer_vals]) for j in range(n)]


def _fold_rng(idx):
    """Scope fleet RNG streams by layer index: the scan body traces ONCE, so
    without the fold every layer would replay identical dropout keys."""
    from contextlib import contextmanager as _cm

    from paddle_tpu.distributed.fleet import rng as fleet_rng

    @_cm
    def scope():
        prev = fleet_rng._tls.active_key_fn
        if prev is not None:
            fleet_rng._tls.active_key_fn = \
                lambda: jax.random.fold_in(prev(), idx)
        try:
            yield
        finally:
            fleet_rng._tls.active_key_fn = prev

    return scope()


def scan_layer_stack(template, stacked_vals: Sequence, x, args: tuple = (),
                     kwargs: dict | None = None, policy: str = "none"):
    """Run a homogeneous layer stack as `jax.lax.scan` over stacked params.

    template: one layer instance (the body is traced through it via
    `functional_call`, so its parameter Tensors are only used as binding
    slots). stacked_vals: one [L, ...] array per template parameter. x: the
    carried hidden-state ARRAY. args/kwargs: broadcast (layer-invariant)
    extras passed to every layer call. Returns the final hidden array.
    """
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.parallel.train_step import functional_call

    kwargs = kwargs or {}
    n_layers = stacked_vals[0].shape[0]

    def body(carry, xs):
        idx = xs[0]
        layer_vals = list(xs[1:])
        with _fold_rng(idx):
            out = functional_call(template, layer_vals, (Tensor(carry),) + args,
                                  kwargs=kwargs)
        return (out._value if isinstance(out, Tensor) else out), None

    body = remat_wrap(body, policy, in_scan=True)
    xs = (jnp.arange(n_layers),) + tuple(stacked_vals)
    h, _ = jax.lax.scan(body, x, xs)
    return h


def unrolled_layer_call(layer, x, args: tuple = (), kwargs: dict | None = None,
                        policy: str = "none"):
    """One layer applied to hidden-state ARRAY `x` with the remat policy as a
    per-layer `jax.checkpoint` region (the unrolled-loop counterpart of
    `scan_layer_stack`); embed/head stay outside the region by construction.
    """
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.parallel.train_step import functional_call

    kwargs = kwargs or {}
    params = layer.parameters()

    def one(hv, *param_vals):
        out = functional_call(layer, list(param_vals), (Tensor(hv),) + args,
                              kwargs=kwargs)
        return out._value if isinstance(out, Tensor) else out

    wrapped = remat_wrap(one, policy)
    from paddle_tpu.core.tensor import apply_op

    return apply_op(wrapped, Tensor(x) if not isinstance(x, Tensor) else x,
                    *params, name="remat_layer")
