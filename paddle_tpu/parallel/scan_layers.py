"""Scan-over-layers compilation + policy-based selective rematerialization.

Reference analog: the recompute pass / `recompute_interval` knob of the
reference's hybrid-parallel stack (fleet recompute, SURVEY §2.1) — but
TPU-native, the T5X/MaxText way:

* **Scan-over-layers.** A homogeneous decoder stack (N identical layers) is
  executed as ONE `jax.lax.scan` over the layer parameters stacked along a
  leading layer axis, so the traced program contains the layer body once and
  HLO size / XLA compile time are O(1) in depth instead of O(N).
* **Selective remat policies.** The all-or-nothing `remat: bool` knob becomes
  a policy string applied PER LAYER via `jax.checkpoint` +
  `jax.checkpoint_policies`:

    - ``none``              no rematerialization (save everything XLA keeps)
    - ``full``              `jax.checkpoint` default: save only layer
                            boundaries, recompute the layer interior
    - ``save_nothing``      explicit `nothing_saveable` (alias of ``full``'s
                            default policy, spelled out)
    - ``save_dots``         `dots_with_no_batch_dims_saveable`: keep matmul
                            outputs, recompute the cheap elementwise tail
    - ``offload_residuals`` residual-stream activations (tagged
                            `checkpoint_name(..., "residual")` by the layer)
                            are offloaded to pinned host memory via
                            `save_and_offload_only_these_names` when the
                            backend has one (`host_memory_supported()`),
                            else saved on device (`save_only_these_names`)

  Because the policy wraps each layer (or the scan body), the embed / fused
  LM-head / CE segment is NEVER inside a remat region: the fused head is
  computed exactly once even under ``full``.

Cooperation protocol (how a compiled step talks to a model):

* A model that can apply per-layer remat itself sets
  ``layer_remat_capable = True`` and reads :func:`current_layer_ctx` in its
  forward. `CompiledTrainStep` then delivers the policy via
  :func:`layer_execution` instead of wrapping the whole loss in
  `jax.checkpoint` (the legacy behavior, kept for non-cooperating models).
* A model whose homogeneous stack can be scanned exposes ``scan_group()``
  returning the list of identical layers. `CompiledTrainStep(scan_layers=
  True)` stacks each layer parameter across the group OUTSIDE the program
  (one `[L, ...]` jit input per parameter) and delivers the stacked arrays
  through the same context; the model consumes them with
  :func:`scan_layer_stack`.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "REMAT_POLICIES", "normalize_remat", "remat_wrap", "layer_execution",
    "current_layer_ctx", "LayerExecContext", "stack_layer_vals",
    "scan_layer_stack", "unrolled_layer_call", "ScanShardInfo",
]

REMAT_POLICIES = ("none", "full", "save_dots", "save_nothing",
                  "offload_residuals")

# checkpoint_name tag the decoder layers put on their residual stream; the
# offload_residuals policy keys on it
RESIDUAL_TAG = "residual"


def normalize_remat(remat) -> str:
    """Map the legacy bool knob onto the policy namespace.

    True -> 'full' (the old whole-graph remat semantics, now applied per
    layer for cooperating models), False/None -> 'none'; policy strings pass
    through validated.
    """
    if remat is None or remat is False:
        return "none"
    if remat is True:
        return "full"
    policy = str(remat)
    if policy not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat policy {policy!r}; expected one of "
            f"{'|'.join(REMAT_POLICIES)} (or a bool)")
    return policy


def _offload_policy():
    from paddle_tpu.parallel.train_step import host_memory_supported

    if host_memory_supported():
        return jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=[RESIDUAL_TAG],
            offload_src="device", offload_dst="pinned_host")
    # no pinned-host space (CPU test backend): degrade to device-saved names,
    # preserving the recompute structure (and numerics) of the offload policy
    return jax.checkpoint_policies.save_only_these_names(RESIDUAL_TAG)


def remat_wrap(fn: Callable, policy: str, in_scan: bool = False) -> Callable:
    """Wrap `fn` (a pure jax function) in `jax.checkpoint` per `policy`.

    `in_scan=True` relaxes `prevent_cse` (safe and faster under
    `lax.scan`/`while`, per the jax.checkpoint docs).
    """
    policy = normalize_remat(policy)
    if policy == "none":
        return fn
    kw = dict(prevent_cse=not in_scan)
    if policy == "save_nothing":
        kw["policy"] = jax.checkpoint_policies.nothing_saveable
    elif policy == "save_dots":
        kw["policy"] = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    elif policy == "offload_residuals":
        kw["policy"] = _offload_policy()
    # 'full': jax.checkpoint's default (save only the wrapped fn's inputs)
    return jax.checkpoint(fn, **kw)


class ScanShardInfo:
    """ZeRO-3 layout contract for a scan-stacked layer group.

    cols: one ``(shard_spec, full_spec)`` PartitionSpec pair PER group column,
    for the PER-LAYER slice (the stacked array minus its leading layer dim).
    ``shard_spec`` is how the column persists between steps (reduce-scattered
    over the sharding axis); ``full_spec`` is its layout while a layer is
    being computed (mp-only sharding). mode: ``"ahead"`` = double-buffered
    gather of layer k+1 while layer k computes (at most 2 layers of full
    weights live); ``"start"`` = all-gather the whole stack up front (the
    overlap-free baseline the bench compares against).
    """

    __slots__ = ("mesh", "cols", "mode", "axis", "act_spec")

    def __init__(self, mesh, cols, mode: str = "ahead", axis: str = "sharding",
                 act_spec=None):
        if mode not in ("ahead", "start"):
            raise ValueError(
                f"unknown zero3 gather mode {mode!r}; expected 'ahead'|'start'")
        self.mesh = mesh
        self.cols = list(cols)
        self.mode = mode
        self.axis = axis
        # layout of the carried hidden state (the step's batch spec): pinning
        # the layer-boundary activations stops the partitioner from resharding
        # the saved boundaries onto the weight axes between fwd and bwd
        self.act_spec = act_spec


class LayerExecContext:
    """What a compiled step asks of a cooperating model's layer stack."""

    __slots__ = ("policy", "stacked", "shard_info")

    def __init__(self, policy: str = "none", stacked=None, shard_info=None):
        self.policy = policy
        # stacked: per-parameter [L, ...] arrays for the model's scan_group()
        # (stacked OUTSIDE the traced program), or None when the model should
        # use its own (bound) per-layer parameters
        self.stacked = stacked
        # shard_info: ScanShardInfo when the stacked arrays persist ZeRO-3
        # reduce-scattered and the scan loop must (un)gather them itself
        self.shard_info = shard_info


class _CtxTLS(threading.local):
    def __init__(self):
        self.ctx = None


_tls = _CtxTLS()


def current_layer_ctx() -> LayerExecContext | None:
    return _tls.ctx


@contextmanager
def layer_execution(policy: str = "none", stacked=None, shard_info=None):
    prev = _tls.ctx
    _tls.ctx = LayerExecContext(policy, stacked, shard_info)
    try:
        yield _tls.ctx
    finally:
        _tls.ctx = prev


def stack_layer_vals(per_layer_vals: Sequence[Sequence]) -> list:
    """list[L][P] parameter values -> list[P] arrays stacked on a new leading
    layer axis (the MaxText/T5X scanned-weights layout)."""
    n = len(per_layer_vals[0])
    for lp in per_layer_vals:
        if len(lp) != n:
            raise ValueError("scan group layers are not homogeneous")
    return [jnp.stack([lp[j] for lp in per_layer_vals]) for j in range(n)]


def _fold_rng(idx):
    """Scope fleet RNG streams by layer index: the scan body traces ONCE, so
    without the fold every layer would replay identical dropout keys."""
    from contextlib import contextmanager as _cm

    from paddle_tpu.distributed.fleet import rng as fleet_rng

    @_cm
    def scope():
        prev = fleet_rng._tls.active_key_fn
        if prev is not None:
            fleet_rng._tls.active_key_fn = \
                lambda: jax.random.fold_in(prev(), idx)
        try:
            yield
        finally:
            fleet_rng._tls.active_key_fn = prev

    return scope()


def scan_layer_stack(template, stacked_vals: Sequence, x, args: tuple = (),
                     kwargs: dict | None = None, policy: str = "none",
                     shard_info: ScanShardInfo | None = None):
    """Run a homogeneous layer stack as `jax.lax.scan` over stacked params.

    template: one layer instance (the body is traced through it via
    `functional_call`, so its parameter Tensors are only used as binding
    slots). stacked_vals: one [L, ...] array per template parameter. x: the
    carried hidden-state ARRAY. args/kwargs: broadcast (layer-invariant)
    extras passed to every layer call. Returns the final hidden array.

    shard_info (ZeRO-3): the stacked arrays persist reduce-scattered over the
    sharding axis. mode "ahead" runs the double-buffered gather-ahead scan
    (layer k+1's weights all-gather while layer k computes; backward
    re-gathers and emits reduce-scatter gradients — at most 2 layers of full
    weights are ever live). mode "start" all-gathers the whole stack before
    the loop (the overlap-free baseline).
    """
    from paddle_tpu.amp import fp8 as _fp8
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.parallel.train_step import functional_call

    kwargs = kwargs or {}
    if shard_info is not None:
        sess = _fp8.current_session()
        if sess is not None and sess.mode != "stateless":
            # the zero3 custom-vjp scan owns its residuals/cotangents and
            # cannot thread the delayed-scaling amax state; CompiledTrainStep
            # rejects the combination up front — this is the backstop
            raise ValueError(
                "fp8 delayed scaling cannot thread the zero_stage=3 "
                "sharded-weights scan; use zero_stage<=2 with fp8_policy")
        return _zero3_scan(template, stacked_vals, x, args, kwargs,
                           shard_info)
    n_layers = stacked_vals[0].shape[0]
    n_cols = len(stacked_vals)
    # delayed-scaling fp8: stacked [L, H] amax histories for the callsites
    # inside the layer body ride the scan xs; their per-layer cotangents
    # (the updated histories) re-stack through the scan's vjp
    fp8_leaves = _fp8.scan_enter(n_layers)

    def body(carry, xs):
        idx = xs[0]
        layer_vals = list(xs[1:1 + n_cols])
        with _fold_rng(idx), _fp8.scan_body(list(xs[1 + n_cols:])):
            out = functional_call(template, layer_vals, (Tensor(carry),) + args,
                                  kwargs=kwargs)
        return (out._value if isinstance(out, Tensor) else out), None

    body = remat_wrap(body, policy, in_scan=True)
    xs = (jnp.arange(n_layers),) + tuple(stacked_vals) + tuple(fp8_leaves)
    h, _ = jax.lax.scan(body, x, xs)
    _fp8.scan_exit()
    return h


def _rng_base_raw():
    """Snapshot the active fleet RNG stream as raw key data (or None).

    The zero3 custom-vjp scan re-traces the layer body when the backward
    re-gathers weights; a thread-local key FUNCTION would be gone (or its
    fold counter advanced) by then, so the per-stack base key is captured
    once as a VALUE and threaded through the vjp explicitly."""
    from paddle_tpu.distributed.fleet import rng as fleet_rng

    fn = fleet_rng._tls.active_key_fn
    if fn is None:
        return None
    return jax.random.key_data(fn())


@contextmanager
def _rng_from_raw(key_raw, idx):
    """Install a per-layer fleet RNG stream derived from captured raw key
    data (the replayable counterpart of `_fold_rng`)."""
    from paddle_tpu.distributed.fleet import rng as fleet_rng

    prev = fleet_rng._tls.active_key_fn
    if key_raw is not None:
        base = jax.random.wrap_key_data(key_raw)
        fleet_rng._tls.active_key_fn = lambda: jax.random.fold_in(base, idx)
    try:
        yield
    finally:
        fleet_rng._tls.active_key_fn = prev


def _zero_cotangent(v):
    """A zero cotangent of the right kind: float0 for integer/key primals."""
    if jnp.issubdtype(v.dtype, jnp.floating) or jnp.issubdtype(
            v.dtype, jnp.complexfloating):
        return jnp.zeros(v.shape, v.dtype)
    return np.zeros(v.shape, jax.dtypes.float0)


def _zero3_scan(template, stacked_vals, x, args, kwargs,
                shard_info: ScanShardInfo):
    """The ZeRO-3 scan loop: double-buffered gather-ahead forward, re-gather
    + reduce-scatter backward, as one `jax.custom_vjp`.

    Why a custom vjp instead of `jax.checkpoint`: the prefetched full weights
    ride the scan CARRY, and anything in the carry is a saved residual under
    every checkpoint policy — plain AD (or remat) would therefore keep ALL L
    layers of gathered weights live for the backward, defeating the sharding.
    Owning the vjp pins the residuals to exactly (layer-boundary activations,
    the reduce-scattered stacks): forward gathers layer k+1 while layer k
    computes; backward runs the mirror-image scan (gather layer k-1 while
    layer k's grads compute), recomputing each layer interior — the
    PyTorch-FSDP/ZeRO-3 schedule, so the layer interior is implicitly
    remat'd 'full' regardless of the session policy.

    Gradients w.r.t. the stacked params leave each backward iteration through
    a `with_sharding_constraint` to the reduce-scattered layout: with the
    batch sharded over the same axis the partial-sum dW lowers to a
    reduce-scatter instead of an all-reduce, and the optimizer update runs
    on the shard.

    mode "start" (the bench baseline) shares this exact vjp structure —
    identical residuals, identical per-layer dW scatter — but gathers the
    WHOLE stack before each loop instead of one layer ahead, so the
    measured difference between the modes is purely the gather schedule."""
    from jax.sharding import NamedSharding, PartitionSpec

    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.parallel.train_step import functional_call

    mesh = shard_info.mesh
    zaxis = shard_info.axis
    zsize = int(mesh.shape[zaxis])
    full_sh = [NamedSharding(mesh, PartitionSpec(*tuple(f)))
               for _, f in shard_info.cols]
    shard_sh = [NamedSharding(mesh, PartitionSpec(*tuple(s)))
                for s, _ in shard_info.cols]
    n_layers = int(stacked_vals[0].shape[0])
    n_cols = len(stacked_vals)

    # -- flat-buffer packing (the FSDP flat-parameter trick) ----------------
    # A layer's columns whose ONLY sharded dim is the zero axis are packed
    # into one [Z, T] buffer, so the layer costs ONE all-gather (and its
    # grads ONE reduce-scatter) instead of one per column — collective
    # launch/rendezvous overhead is what eats the overlap win otherwise.
    # Columns that also carry mp sharding keep the per-column path (packing
    # would flatten the mp dim into the buffer and un-shard it).
    slice_shapes = [tuple(v.shape[1:]) for v in stacked_vals]
    packed_cols = []  # (col_index, sharded_dim, flat_size_per_group)
    loose_cols = []
    for i, (s, f) in enumerate(shard_info.cols):
        sdims = tuple(s)
        d = next((j for j, e in enumerate(sdims) if e == zaxis), None)
        only_zero = all(e is None for j, e in enumerate(sdims) if j != d) \
            and all(e is None for e in tuple(f))
        if d is not None and only_zero and slice_shapes[i]:
            packed_cols.append((i, d))
        else:
            loose_cols.append(i)

    def _pack(vals):
        """Per-layer column slices -> ONE [Z, T] buffer (pure local reshapes:
        the sharded dim moves to the front and splits into Z groups)."""
        groups = []
        for i, d in packed_cols:
            v = jnp.moveaxis(vals[i], d, 0)
            groups.append(v.reshape((zsize, -1)))
        return jnp.concatenate(groups, axis=1)

    def _unpack(packed):
        """[Z, T] buffer -> per-layer column slices (inverse of `_pack`)."""
        out = {}
        off = 0
        for i, d in packed_cols:
            shape = slice_shapes[i]
            moved = (shape[d],) + shape[:d] + shape[d + 1:]
            sz = int(np.prod(moved)) // zsize
            piece = packed[:, off:off + sz]
            off += sz
            v = piece.reshape((zsize, moved[0] // zsize) + moved[1:])
            v = v.reshape(moved)
            out[i] = jnp.moveaxis(v, 0, d)
        return out

    pack_full_sh = NamedSharding(mesh, PartitionSpec())
    pack_shard_sh = NamedSharding(mesh, PartitionSpec(zaxis))

    def gather(vals):
        """Reconstitute one layer's full weights: one packed all-gather +
        per-column gathers for the mp-sharded leftovers."""
        out = list(vals)
        if packed_cols:
            packed = jax.lax.with_sharding_constraint(_pack(vals),
                                                      pack_shard_sh)
            full = jax.lax.with_sharding_constraint(packed, pack_full_sh)
            for i, v in _unpack(full).items():
                out[i] = v
        for i in loose_cols:
            out[i] = jax.lax.with_sharding_constraint(vals[i], full_sh[i])
        return out

    def scatter(grads):
        """One layer's full dW -> the reduce-scattered layout: one packed
        reduce-scatter + per-column constraints for the leftovers."""
        out = list(grads)
        if packed_cols:
            packed = jax.lax.with_sharding_constraint(_pack(grads),
                                                      pack_shard_sh)
            for i, v in _unpack(packed).items():
                out[i] = jax.lax.with_sharding_constraint(v, shard_sh[i])
        for i in loose_cols:
            out[i] = jax.lax.with_sharding_constraint(grads[i], shard_sh[i])
        return out

    act_sh = (NamedSharding(mesh, PartitionSpec(*tuple(shard_info.act_spec)))
              if shard_info.act_spec is not None else None)

    def pin_act(h):
        return (jax.lax.with_sharding_constraint(h, act_sh)
                if act_sh is not None else h)

    # broadcast extras (attn_mask / rope / segment metadata) must be explicit
    # vjp primals: custom_vjp functions may not close over outer-jit tracers
    extra_leaves, extra_tree = jax.tree_util.tree_flatten(
        (tuple(args), dict(kwargs)),
        is_leaf=lambda v: isinstance(v, Tensor))
    extra_arrs, extra_slots, extra_static = [], [], []
    for leaf in extra_leaves:
        v = leaf._value if isinstance(leaf, Tensor) else leaf
        if isinstance(v, (jax.Array, np.ndarray)) or hasattr(v, "dtype"):
            extra_slots.append(len(extra_arrs))
            extra_arrs.append(jnp.asarray(v))
            extra_static.append(None)
        else:
            extra_slots.append(None)
            extra_static.append(leaf)

    def rebuild_extras(arrs):
        leaves = [extra_static[i] if s is None else arrs[s]
                  for i, s in enumerate(extra_slots)]
        return jax.tree_util.tree_unflatten(extra_tree, leaves)

    key_raw = _rng_base_raw()
    has_rng = key_raw is not None
    if key_raw is None:
        key_raw = jnp.zeros((2,), jnp.uint32)  # placeholder primal slot

    def apply_layer(idx, w_full, h, kraw, extras):
        a, kw = rebuild_extras(extras)
        with _rng_from_raw(kraw if has_rng else None, idx):
            out = functional_call(template, list(w_full),
                                  (Tensor(h),) + tuple(a), kwargs=kw)
        return out._value if isinstance(out, Tensor) else out

    ahead = shard_info.mode == "ahead"
    stacked_full_sh = [
        NamedSharding(mesh, PartitionSpec(None, *tuple(f)))
        for _, f in shard_info.cols]

    def gather_stack(stacked):
        """mode 'start': unshard every layer's weights up front."""
        return [jax.lax.with_sharding_constraint(v, sh)
                for v, sh in zip(stacked, stacked_full_sh)]

    def _fwd_scan(h0, kraw, stacked, extras):
        if not ahead:
            full = gather_stack(stacked)

            def body0(carry, xs):
                idx, cur = xs[0], list(xs[1:])
                h2 = pin_act(apply_layer(idx, cur, carry, kraw, extras))
                return h2, carry

            return jax.lax.scan(
                body0, h0, (jnp.arange(n_layers),) + tuple(full))
        first = gather([v[0] for v in stacked])
        # iteration k's xs slice carries layer k+1's shards (last wraps to 0:
        # one redundant tail gather keeps the loop homogeneous)
        rolled = [jnp.roll(v, -1, axis=0) for v in stacked]

        def body(carry, xs):
            idx, nxt = xs[0], list(xs[1:])
            h, cur = carry
            nxt_full = gather(nxt)  # layer idx+1, overlaps layer idx compute
            h2 = pin_act(apply_layer(idx, cur, h, kraw, extras))
            return (h2, nxt_full), h  # ys: layer k's INPUT activation

        (h, _), bounds = jax.lax.scan(
            body, (h0, first), (jnp.arange(n_layers),) + tuple(rolled))
        return h, bounds

    @jax.custom_vjp
    def run(h0, kraw, *rest):
        stacked, extras = rest[:n_cols], rest[n_cols:]
        h, _ = _fwd_scan(h0, kraw, stacked, extras)
        return h

    def run_fwd(h0, kraw, *rest):
        stacked, extras = rest[:n_cols], rest[n_cols:]
        h, bounds = _fwd_scan(h0, kraw, stacked, extras)
        return h, (kraw, bounds, stacked, extras)

    def run_bwd(res, g):
        kraw, bounds, stacked, extras = res

        def layer_vjp(idx, cur, h_in, dh):
            def relin(w_full, h):
                return apply_layer(idx, w_full, h, kraw, extras)

            _, vjp = jax.vjp(relin, cur, h_in)
            dw_full, dh_in = vjp(dh)
            return tuple(scatter(list(dw_full))), pin_act(dh_in)

        if not ahead:
            full = gather_stack(stacked)

            def body0(carry, xs):
                idx, h_in, cur = xs[0], xs[1], list(xs[2:])
                dws, dh_in = layer_vjp(idx, cur, h_in, carry)
                return dh_in, dws

            dx, dws = jax.lax.scan(
                body0, g, (jnp.arange(n_layers), bounds) + tuple(full),
                reverse=True)
        else:
            last = gather([v[n_layers - 1] for v in stacked])
            # iteration k's xs slice carries layer k-1's shards (k=0 wraps)
            rolled = [jnp.roll(v, 1, axis=0) for v in stacked]

            def body(carry, xs):
                idx, h_in, prev = xs[0], xs[1], list(xs[2:])
                dh, cur = carry
                prev_full = gather(prev)  # layer idx-1 overlaps idx's bwd
                dws, dh_in = layer_vjp(idx, cur, h_in, dh)
                return (dh_in, prev_full), dws

            (dx, _), dws = jax.lax.scan(
                body, (g, last),
                (jnp.arange(n_layers), bounds) + tuple(rolled), reverse=True)
        return (dx, _zero_cotangent(kraw)) + tuple(dws) + tuple(
            _zero_cotangent(e) for e in extras)

    run.defvjp(run_fwd, run_bwd)
    return run(x, key_raw, *tuple(stacked_vals), *tuple(extra_arrs))


def unrolled_layer_call(layer, x, args: tuple = (), kwargs: dict | None = None,
                        policy: str = "none"):
    """One layer applied to hidden-state ARRAY `x` with the remat policy as a
    per-layer `jax.checkpoint` region (the unrolled-loop counterpart of
    `scan_layer_stack`); embed/head stay outside the region by construction.
    """
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.parallel.train_step import functional_call

    kwargs = kwargs or {}
    params = layer.parameters()

    def one(hv, *param_vals):
        out = functional_call(layer, list(param_vals), (Tensor(hv),) + args,
                              kwargs=kwargs)
        return out._value if isinstance(out, Tensor) else out

    wrapped = remat_wrap(one, policy)
    from paddle_tpu.core.tensor import apply_op

    return apply_op(wrapped, Tensor(x) if not isinstance(x, Tensor) else x,
                    *params, name="remat_layer")
