"""Batch-context delivery of packed-sequence metadata to layers.

The pipelined runtimes (1F1B `PipelinedTrainStep`, ZB-H1
`ZBH1PipelinedStep`) move only the hidden-state activation between stages;
per-token batch metadata — the `segment_ids`/`position_ids` a packed batch
carries — cannot ride the activation without changing every stage's wire
format. Instead the runtimes publish the CURRENT microbatch's metadata in a
thread-local context for the duration of each stage call, and segment-aware
layers (e.g. `LlamaAttention`) read it when their explicit
`segment_ids`/`position_ids` kwargs are None.

This mirrors the scan/remat cooperation protocol
(`paddle_tpu.parallel.scan_layers.layer_execution`): tracing is ordinary
Python execution, so a context set around a `functional_call` is visible to
every layer the call traces, and the traced values are captured into the
program like any other closure tracer. Layers that ignore the context are
untouched — publishing metadata to an MLP block is a no-op.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

__all__ = ["segment_execution", "current_segment_ctx", "SegmentContext"]


class SegmentContext:
    """segment_ids / position_ids of the microbatch currently being traced
    ([mb, S] arrays, or None for the unpacked case)."""

    __slots__ = ("segment_ids", "position_ids")

    def __init__(self, segment_ids=None, position_ids=None):
        self.segment_ids = segment_ids
        self.position_ids = position_ids


class _TLS(threading.local):
    def __init__(self):
        self.ctx = None


_tls = _TLS()


def current_segment_ctx() -> SegmentContext | None:
    return _tls.ctx


@contextmanager
def segment_execution(segment_ids=None, position_ids=None):
    """Publish packed-batch metadata to the layers traced inside the block.
    A no-op context (both None) still masks any outer one, so nested stages
    never leak another microbatch's ids."""
    prev = _tls.ctx
    _tls.ctx = SegmentContext(segment_ids, position_ids)
    try:
        yield _tls.ctx
    finally:
        _tls.ctx = prev
