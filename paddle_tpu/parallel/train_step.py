"""Compiled SPMD train step — the performance path.

Reference analog: the whole static-graph pipeline (to_static -> StandaloneExecutor
-> PirInterpreter, SURVEY §3.5) plus EagerReducer's fused-overlapped gradient
sync (reducer.cc:1093). TPU-native: ONE jitted XLA program computes
loss -> grads -> optimizer update with:
  - parameters/optimizer state living as device arrays between steps (donated,
    so updates are in-place in HBM),
  - shardings from the mesh: batch dim 0 over "dp"/"sharding", the
    SEQUENCE dim over "sep" (context parallelism), params over
    "mp" (from the `_mp_pspec` annotations the TP layers attach), optimizer
    state over "sharding"/"dp" for ZeRO,
  - XLA inserting + overlapping all collectives (grad psum over dp ≈ the
    reference's fused allreduce; state sharding ≈ reduce-scatter of ZeRO).
Dropout gets a per-step folded key threaded through the program so compiled
training is stochastically correct (the RNGStatesTracker analog under jit).
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from paddle_tpu.autograd import tape as _tape
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.fleet import rng as fleet_rng
from paddle_tpu.distributed.mesh import get_mesh
from paddle_tpu.distributed.resilience import faults

__all__ = ["CompiledTrainStep", "functional_call", "init_opt_states",
           "apply_optimizer_update"]

faults.register(
    "step.grads",
    "poison one training step (fire_check site in CompiledTrainStep): "
    "NaN-scales the first float batch leaf (NaN grads — the in-program "
    "health check catches it the SAME step and skips the update) or, for "
    "integer-only batches, the learning rate (params corrupted — caught "
    "on the NEXT step's non-finite loss; only rollback recovers)")


def _nan_poison(vals):
    """Chaos helper for the `step.grads` point: NaN-scale the first
    floating batch leaf. Returns (vals, poisoned?) — False means the batch
    has no float leaf (token ids) and the caller poisons the lr instead."""
    if isinstance(vals, dict):
        for k in sorted(vals):
            v = vals[k]
            if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
                out = dict(vals)
                out[k] = v * jnp.asarray(float("nan"), v.dtype)
                return out, True
        return vals, False
    out = list(vals)
    for i, v in enumerate(out):
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
            out[i] = v * jnp.asarray(float("nan"), v.dtype)
            return tuple(out), True
    return vals, False


def _abstractify(x):
    """ShapeDtypeStruct mirror of one step argument leaf (sharding kept
    when present) — concrete arrays are donated per step, so the abstract
    mirror is what `CompiledTrainStep.cost_analysis()` lowers against."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        sh = getattr(x, "sharding", None)
        try:
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
        except TypeError:
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
    return x


def _innermost_opt(opt):
    """Walk wrapper chains (HybridParallelOptimizer etc.) to the optimizer
    whose _state/_step_count feed state_dict()."""
    seen = set()
    while id(opt) not in seen:
        seen.add(id(opt))
        inner = opt.__dict__.get("_inner_opt")
        if inner is None:
            break
        opt = inner
    return opt


def sync_pipeline_states_to_optimizer(optimizer, states, embed_params,
                                      head_params, block_params, unstack,
                                      step_i):
    """Shared checkpoint-parity sync for the pipelined runtimes
    (PipelinedTrainStep / ZBH1PipelinedStep): flat [embed..., stacked-blocks
    ..., head...] states written into the INNERMOST optimizer's _state, with
    stacked block states split per layer via `unstack`."""
    opt = _innermost_opt(optimizer)
    ne = len(embed_params)
    nh = len(head_params)
    nb = len(states) - ne - nh
    for p, st in zip(embed_params, states[:ne]):
        opt._state[id(p)] = dict(st)
    for p, st in zip(head_params, states[ne + nb:]):
        opt._state[id(p)] = dict(st)
    for i, st in enumerate(states[ne:ne + nb]):
        flat = {k: unstack(v) for k, v in st.items()}
        for l, bp in enumerate(block_params):
            opt._state[id(bp[i])] = {k: v[l] for k, v in flat.items()}
    opt._step_count = step_i


def init_opt_states(optimizer, vals, params=None, block_params=None,
                    stack=None):
    """Per-array optimizer state, co-located with its (sharded) value —
    shared by the compiled pipeline runtimes.

    With `params`/`block_params`/`stack`, entries RESUME from a loaded
    checkpoint's optimizer._state instead of starting from zero moments:
    `params` aligns embed/head entries with their Parameter (None marks a
    stacked block column), `block_params[l][i]` is layer l's parameter behind
    stacked column i, and `stack` maps the per-layer state arrays into the
    runtime's stacked block layout (the inverse of its `_unstack`). Columns
    whose per-layer states are missing or mismatched re-init fresh — the same
    granularity as CompiledTrainStep._resume_states."""
    existing = getattr(optimizer, "_state", {}) if params is not None else {}
    states = []
    col_i = 0

    def _shapes_ok(st, v):
        # a stale-shaped moment (e.g. a resized embedding) must re-init
        # fresh, not explode later inside the optimizer update
        return all(tuple(np.shape(s)) in ((), tuple(v.shape))
                   for s in st.values())

    for idx, v in enumerate(vals):
        p = params[idx] if params is not None else None
        st = None
        if p is not None:
            saved = existing.get(id(p))
            if saved:
                st = dict(saved)
        elif params is not None and block_params is not None:
            col = [bp[col_i] for bp in block_params]
            col_i += 1
            sts = [existing.get(id(cp)) for cp in col]
            if any(s is not None for s in sts) and stack is not None:
                if (all(s is not None for s in sts)
                        and len({frozenset(s) for s in sts}) == 1):
                    try:
                        st = {k: stack([jnp.asarray(s[k]) for s in sts])
                              for k in sts[0]}
                    except (ValueError, TypeError):
                        import warnings

                        # heterogeneous per-layer shapes cannot stack —
                        # same warn-and-reinit contract as below
                        warnings.warn(
                            "pipeline resume: per-layer optimizer state "
                            "shapes are heterogeneous; reinitializing the "
                            "stacked entry's moments from zero")
                        st = None
                else:
                    import warnings

                    warnings.warn(
                        "pipeline resume: per-layer optimizer states are "
                        "incomplete or have mismatched keys; reinitializing "
                        "the stacked entry's moments from zero")
        if st is not None and not _shapes_ok(st, v):
            import warnings

            warnings.warn(
                "pipeline resume: restored optimizer state shapes do not "
                "match the parameter; reinitializing that entry's moments "
                "from zero")
            st = None
        if st is None:
            st = optimizer._init_state(Tensor(v))
        st = {k: jax.device_put(jnp.asarray(s), v.sharding)
              for k, s in st.items()}
        states.append(st)
    return states


def apply_optimizer_update(optimizer, params, grads, states, lr, step_i):
    """Pure (jit-safe) update loop over flat array lists: dtype-cast grads,
    honor the optimizer's grad_clip (global-norm / per-tensor norm / value,
    the nn.clip semantics on raw arrays), then optimizer._update per array.
    The single implementation behind PipelinedTrainStep and
    ZBH1PipelinedStep — schedule runtimes must not drift apart here."""
    grads = [g.astype(p.dtype) if g.dtype != p.dtype else g
             for p, g in zip(params, grads)]
    clip = getattr(optimizer, "_grad_clip", None)
    if clip is not None:
        from paddle_tpu.nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                                        ClipGradByValue)

        if isinstance(clip, ClipGradByGlobalNorm):
            gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                              for g in grads))
            f = jnp.where(gn > clip.clip_norm,
                          clip.clip_norm / jnp.maximum(gn, 1e-12), 1.0)
            grads = [g * f.astype(g.dtype) for g in grads]
        elif isinstance(clip, ClipGradByNorm):
            out = []
            for g in grads:
                n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
                f = jnp.where(n > clip.clip_norm,
                              clip.clip_norm / jnp.maximum(n, 1e-12), 1.0)
                out.append(g * f.astype(g.dtype))
            grads = out
        elif isinstance(clip, ClipGradByValue):
            grads = [jnp.clip(g, clip.min, clip.max) for g in grads]
    new_p, new_s = [], []
    for pv, gv, st in zip(params, grads, states):
        np_, ns_ = optimizer._update(pv, gv, st, lr, step_i)
        new_p.append(np_)
        new_s.append(ns_)
    return new_p, new_s


def _param_pspec(p: Tensor, mesh: Mesh | None) -> PartitionSpec:
    spec = getattr(p, "_mp_pspec", None)
    if mesh is None or spec is None:
        return PartitionSpec()
    dims = []
    for s in spec:
        if s is not None and s in mesh.shape and mesh.shape[s] > 1:
            dims.append(s)
        else:
            dims.append(None)
    return PartitionSpec(*dims)


def _state_pspec(p_spec: PartitionSpec, state_val, axis: str | None, mesh: Mesh | None,
                 start_dim: int = 0):
    """ZeRO: shard optimizer state over `axis` on the FIRST dim that is not
    already mp-sharded and is divisible — an mp-sharded table (dim 0 over
    'mp') still gets its moments dp-sharded on dim 1, so per-device state is
    1/(mp*dp) of the total (the PS-scale sparse-table layout).

    start_dim: first dim eligible for the axis. Scan-stacked group columns
    pass 1 — their dim 0 is the LAYER axis the scan slices per iteration,
    and sharding it would make every iteration's state slice (and the grad
    accumulator the partitioner propagates it onto) a cross-device gather."""
    if mesh is None or axis is None or axis not in mesh.shape or mesh.shape[axis] <= 1:
        return p_spec
    dims = list(p_spec) + [None] * (state_val.ndim - len(list(p_spec)))
    if state_val.ndim == 0:
        return PartitionSpec()
    flat_axes = [a for entry in dims if entry
                 for a in (entry if isinstance(entry, tuple) else (entry,))]
    if axis not in flat_axes:  # zero-3 already shards params over `axis`
        for d in range(start_dim, state_val.ndim):
            if dims[d] is None and state_val.shape[d] % mesh.shape[axis] == 0:
                dims[d] = axis
                break
    return PartitionSpec(*dims[: state_val.ndim])


def _zero3_param_spec(spec: PartitionSpec, val, axis: str | None, mesh: Mesh | None):
    """ZeRO-3: persist the parameter itself sharded on dim 0 over `axis`
    (GSPMD all-gathers on use inside the step — the reference stage-3
    forward-pre-hook allgather, group_sharded_stage3.py:85)."""
    if (mesh is None or axis is None or axis not in mesh.shape
            or mesh.shape[axis] <= 1 or val.ndim == 0):
        return spec
    dims = list(spec) + [None] * (val.ndim - len(list(spec)))
    if dims[0] is None and axis not in dims and val.shape[0] % mesh.shape[axis] == 0:
        dims[0] = axis
        return PartitionSpec(*dims[: val.ndim])
    return spec


def _zero3_stacked_spec(spec: PartitionSpec, val, axis: str | None,
                        mesh: Mesh | None):
    """ZeRO-3 layout for a scan-stacked [L, ...] group column: shard the
    first free, divisible NON-layer dim over `axis` (dim 0 is the scan axis —
    sharding it would make the per-iteration layer slice a cross-device
    gather). Returns (spec, sharded?); the scan loop re-gathers per layer
    (scan_layers gather-ahead), so unlike `_zero3_param_spec` this is NOT a
    leave-it-to-GSPMD layout."""
    if (mesh is None or axis is None or axis not in mesh.shape
            or mesh.shape[axis] <= 1 or val.ndim <= 1):
        return spec, False
    dims = list(spec) + [None] * (val.ndim - len(list(spec)))
    flat_axes = [a for entry in dims if entry
                 for a in (entry if isinstance(entry, tuple) else (entry,))]
    if axis in flat_axes:
        return spec, False
    for d in range(1, val.ndim):
        if dims[d] is None and val.shape[d] % mesh.shape[axis] == 0:
            dims[d] = axis
            return PartitionSpec(*dims[: val.ndim]), True
    return spec, False


def host_memory_supported() -> bool:
    """True when the backend exposes a pinned-host memory space (TPU does;
    the CPU test backend does not — offload then degrades to device)."""
    try:
        dev = jax.local_devices()[0]
        return any(m.kind == "pinned_host" for m in dev.addressable_memories())
    except Exception:
        return False


def functional_call(model, params_vals: Sequence, args, kwargs=None, training=True,
                    method=None, params=None):
    """Run `model` with its parameters temporarily bound to `params_vals`
    (possibly tracers). All paddle_tpu ops are pure jax fns of Tensor._value,
    so ordinary Python execution under tracers IS the graph capture.
    `method` names an alternative entry point (e.g. "forward_features" for
    the fused-head protocol) instead of `model.__call__`. `params` restricts
    the binding to a subset of the model's parameters (scan-over-layers
    packing binds only the non-stacked ones; the stacked group arrives via
    the layer-execution context instead)."""
    kwargs = kwargs or {}
    params = model.parameters() if params is None else params
    old = [p._value for p in params]
    try:
        for p, v in zip(params, params_vals):
            p._set_value(v)
        t_args = [Tensor(a) if isinstance(a, jax.Array) else a for a in args]
        t_kwargs = {k: Tensor(v) if isinstance(v, jax.Array) else v
                    for k, v in kwargs.items()}
        fn = getattr(model, method) if method else model
        with _tape.no_grad():
            out = fn(*t_args, **t_kwargs)
        return out
    finally:
        for p, v in zip(params, old):
            p._set_value(v)


class CompiledTrainStep:
    """Compile (model, loss_fn, optimizer) into one sharded XLA program.

    batch_spec: PartitionSpec for each batch input (default: shard dim0 over
    every data-like axis present in the mesh).
    zero_axis: mesh axis for ZeRO sharding; None = off.
    zero_stage: 1/2 = optimizer state sharded over zero_axis (grad
      reduce-scatter is GSPMD's choice once the update is sharded); 3 = the
      parameters themselves are ALSO persisted sharded. With scan_layers the
      stacked decoder columns persist reduce-scattered on a non-layer dim
      and the scan loop gathers them back per layer; without scan packing
      (or for the embed/head outer params) GSPMD gathers on use.
    zero3_gather: 'ahead' (default, the `zero3_gather` flag) = double-
      buffered gather-ahead — layer k+1's weights all-gather while layer k
      computes and backward re-gathers + reduce-scatters grads, so at most
      2 layers of full weights are ever live; 'start' = all-gather the whole
      stack before the loop (the overlap-free baseline bench.py compares
      against).
    offload_optimizer: place optimizer state in pinned host memory
      (reference sharding offload variants); requires backend host-memory
      support (TPU), silently stays in HBM otherwise.
    metrics_every: pacing for `step_async` — every k-th returned LossFuture
      comes pre-blocked (already finished, so reading it is free). k=1 (the
      `metrics_sync_every` flag default) keeps fully synchronous pacing;
      0 never blocks, leaving run-ahead bounded only by dispatch_window.
      None reads the flag. `__call__` itself never blocks on the loss.
    dispatch_window: max un-fetched steps in flight before dispatch blocks
      on the oldest loss (None reads the `async_dispatch_window` flag).
      Bounds async run-ahead so queued steps' batches can't OOM HBM.
    remat: selective-rematerialization policy — a string from
      paddle_tpu.parallel.scan_layers.REMAT_POLICIES
      (none|full|save_dots|save_nothing|offload_residuals), a bool
      (back-compat: True -> 'full', False -> 'none'), or None to read the
      `remat_policy` flag. Cooperating models (`layer_remat_capable`) get the
      policy applied PER LAYER, so the embed/fused-head/CE segment is never
      recomputed; other models fall back to the legacy whole-loss
      `jax.checkpoint` region (with the policy attached).
    fp8_policy: low-precision matmul policy (mirrors remat_policy):
      'none' | 'matmuls' | 'matmuls+head', or None to read the `fp8_policy`
      flag. 'matmuls' runs the model's F.linear projections through
      float8_e4m3 (gradients float8_e5m2) with DELAYED scaling: per-tensor
      amax histories live as an explicit fp8-state pytree threaded through
      the step exactly like optimizer state (discovered by one abstract
      trace on the first call; stacked [L, H] for callsites inside the
      lax.scan layer loop; checkpoint via fp8_state_dict/load_fp8_state).
      '+head' additionally quantizes the fused-CE head projection (softmax
      stats stay fp32). Composes with zero_axis ZeRO-1/2 (the amax state
      rides replicated next to its stack column); the zero_stage=3
      sharded-weights scan owns its vjp residuals and rejects fp8.
    grad_scaler: an amp.GradScaler for float16 training: the loss is
      scaled inside the program, gradients are unscaled in fp32, and a
      non-finite gradient skips the whole optimizer update (params AND
      moments keep their old values). The scaler's state machine is
      advanced from the per-step found_inf scalar WITHOUT breaking async
      dispatch: flags settle lazily as their device values become ready
      (drain() settles all), so the scale a queued step uses may lag by the
      in-flight window — the documented async-AMP semantics.
    anomaly_detector: in-program anomaly detection (docs/resilience.md):
      an `resilience.AnomalyDetector` (or True for a flag-configured one;
      None reads the `anomaly_detection` flag; False forces off). When on,
      the step computes a health scalar (non-finite loss or grads) INSIDE
      the program — an unhealthy step skips the whole optimizer update,
      exactly like the GradScaler found_inf path — and settles it into the
      detector lazily (only ready buffers are read), so `step_async`
      run-ahead never blocks on detection. The detector additionally flags
      host-side loss spikes (rolling median+MAD) and records/escalates per
      its policy; the resilience supervisor or Model.fit(resilience=) act
      on the escalations.
    collect_metrics: honest per-step telemetry (docs/observability.md):
      the step additionally returns a small metrics side-pytree — fp32
      loss, GLOBAL grad-norm (post-unscale), the found_inf/skip flag, and
      (with fp8) the amax watermark — as replicated device scalars that
      settle lazily on the host (`last_metrics()`, `settle_metrics()`);
      run-ahead is never broken by collection, and the output structure is
      stable so enabling it costs ONE compile, zero retraces. None reads
      the `step_telemetry` flag. `cost_analysis()`/`flops_per_step()`
      expose XLA's own cost model for the compiled step (what MFU gauges
      derive from).
    scan_layers: stack the model's `scan_group()` layer parameters along a
      leading layer axis OUTSIDE the program and run the stack as one
      `lax.scan` — HLO size and compile time become O(1) in depth. None reads
      the `scan_layers` flag. State-dict layout, per-layer optimizer resume,
      and `sync_params_to_model`/`sync_states_to_optimizer` round-trips are
      preserved (stacked arrays are split back per layer on sync).
    """

    def __init__(self, model, loss_fn: Callable, optimizer=None, mesh: Mesh | None = None,
                 batch_spec: PartitionSpec | None = None, zero_axis: str | None = None,
                 zero_stage: int = 1, offload_optimizer: bool = False,
                 donate: bool = True, remat: bool | str | None = None,
                 scan_layers: bool | None = None, seed: int = 0,
                 metrics_every: int | None = None,
                 dispatch_window: int | None = None,
                 zero3_gather: str | None = None,
                 fp8_policy: str | None = None, grad_scaler=None,
                 anomaly_detector=None, collect_metrics: bool | None = None):
        from paddle_tpu.amp.fp8 import normalize_fp8_policy
        from paddle_tpu.core.flags import flag
        from paddle_tpu.io.device_feed import DispatchWindow
        from paddle_tpu.parallel.scan_layers import normalize_remat

        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh if mesh is not None else get_mesh()
        self._params = model.parameters()
        self.remat_policy = normalize_remat(
            flag("remat_policy") if remat is None else remat)
        self.remat = self.remat_policy != "none"
        self.fp8_policy = normalize_fp8_policy(
            flag("fp8_policy") if fp8_policy is None else fp8_policy)
        self._fp8_hist_len = int(flag("fp8_amax_history_len"))
        self._fp8_states = None   # discovered on the first call
        self._fp8_layout = None
        self._scaler = (grad_scaler if grad_scaler is not None
                        and grad_scaler.is_enable() else None)
        self._pending_inf: list = []
        # in-program anomaly detection (docs/resilience.md): None reads the
        # anomaly_detection flag, True builds a flag-configured detector,
        # False forces OFF, an AnomalyDetector instance is used as-is
        from paddle_tpu.distributed.resilience.anomaly import AnomalyDetector
        if anomaly_detector is None:
            anomaly_detector = bool(flag("anomaly_detection"))
        if anomaly_detector is True:
            anomaly_detector = AnomalyDetector()
        self._anomaly_det = (anomaly_detector
                             if isinstance(anomaly_detector, AnomalyDetector)
                             else None)
        self._anomaly = self._anomaly_det is not None
        if (self._anomaly and self._scaler is not None
                and getattr(self._scaler, "_enable", True)
                and getattr(self._scaler, "_dynamic", True)
                and not getattr(self._anomaly_det, "tolerance_explicit",
                                False)
                and self._anomaly_det.nonfinite_tolerance == 0):
            # a dynamic loss scaler OVERFLOWS by design at every growth
            # interval (the skip + scale-halving is the recovery); only a
            # non-finite STREAK the scaler can't break is a real anomaly
            self._anomaly_det.nonfinite_tolerance = 2
        self._pending_health: list = []
        # honest step telemetry (docs/observability.md): the step returns a
        # metrics side-pytree; settled LAZILY like the health scalar, so
        # collection never breaks step_async run-ahead. None reads the
        # step_telemetry flag.
        self._telemetry = bool(flag("step_telemetry")
                               if collect_metrics is None
                               else collect_metrics)
        # layout of the packed per-step metrics vector (one readback/step)
        self._metric_keys = (["loss", "grad_norm", "skipped"]
                             + (["fp8_amax_max"]
                                if self.fp8_policy != "none" else []))
        # MoE models additionally report the summed load-balance aux loss
        # and dropped-token count through the same packed vector (the
        # layers' in-trace stats are read after the forward; under the
        # legacy whole-loss remat region those tracers are scoped to the
        # checkpoint, so collection is limited to remat-off steps)
        self._moe_layers = []
        if self._telemetry and not self.remat:
            from paddle_tpu.incubate.distributed.models.moe import MoELayer

            self._moe_layers = [
                l for l in getattr(model, "sublayers", lambda: [])()
                if isinstance(l, MoELayer)]
        if self._moe_layers:
            self._metric_keys += ["moe_aux", "moe_dropped"]
        self._pending_metrics: list = []
        self._last_metrics: dict | None = None
        self._prev_metric_wall: float | None = None
        self._abstract_args = None       # captured on the first dispatch
        self._cost_analysis_cache = None
        self._layer_capable = bool(getattr(model, "layer_remat_capable", False))
        if scan_layers is None:
            scan_layers = bool(flag("scan_layers"))

        # ---- scan-over-layers packing --------------------------------------
        # outer params bind through functional_call as before; each column j
        # of the homogeneous scan_group becomes ONE stacked [L, ...] value
        self.scan_layers = False
        self._outer_params = self._params
        self._group_cols: list[list] = []  # [P][L] per-layer Parameters
        # packing requires BOTH halves of the cooperation protocol: a model
        # that only exposes scan_group() but never reads the layer-execution
        # context would trace its own (unbound) param values as constants and
        # train frozen weights. It also requires an ELEMENTWISE optimizer
        # update: Lamb/Lars compute a per-PARAMETER trust-ratio norm, which
        # over a stacked [L, ...] entry would couple all layers into one
        # ratio — silently different math than the unrolled run.
        if scan_layers and not self._layer_capable:
            scan_layers = False
        if scan_layers and optimizer is not None:
            from paddle_tpu.optimizer import Lamb, Lars

            if isinstance(_innermost_opt(optimizer), (Lamb, Lars)):
                scan_layers = False
        if scan_layers:
            sg = getattr(model, "scan_group", None)
            group = list(sg()) if callable(sg) else []
            if len(group) >= 2:
                per_layer = [list(l.parameters()) for l in group]
                n_per = len(per_layer[0])
                flat_group = [p for lp in per_layer for p in lp]
                own = {id(p) for p in self._params}
                ok = (n_per > 0
                      and all(len(lp) == n_per for lp in per_layer)
                      and all(not p.stop_gradient for p in flat_group)
                      and len({id(p) for p in flat_group}) == len(flat_group)
                      and all(id(p) in own for p in flat_group))
                if ok:
                    gid = {id(p) for p in flat_group}
                    self._outer_params = [p for p in self._params
                                          if id(p) not in gid]
                    self._group_cols = [[lp[j] for lp in per_layer]
                                        for j in range(n_per)]
                    self.scan_layers = True
        self._trainable = ([not p.stop_gradient for p in self._outer_params]
                           + [True] * len(self._group_cols))
        self.zero_stage = zero_stage
        # offload needs the mesh-based shardings to stream states H2D in-step
        self._offload = (offload_optimizer and host_memory_supported()
                         and (mesh is not None or get_mesh() is not None))

        if batch_spec is None and self.mesh is not None:
            # batch dim 0 over the data axes, the SEQUENCE dim over 'sep'
            # (context parallelism) — shared with DeviceFeeder via
            # device_feed.default_batch_spec
            from paddle_tpu.io.device_feed import default_batch_spec

            batch_spec = default_batch_spec(self.mesh)
        self.batch_spec = batch_spec or PartitionSpec()
        # per-input trimmed shardings are computed ONCE per batch signature
        # (shapes+dtypes) and cached — not per step on the critical path
        from paddle_tpu.io.device_feed import BatchSpecCache

        self._spec_cache = BatchSpecCache(self.mesh, self.batch_spec)
        self.h2d_transfers = 0  # input leaves actually moved host->device
        self.metrics_every = int(flag("metrics_sync_every")
                                 if metrics_every is None else metrics_every)
        self._async_count = 0
        self._window = DispatchWindow(dispatch_window)

        # packed layout: [outer params..., one stacked array per group column]
        packed_vals = [p._value for p in self._outer_params]
        packed_specs = [_param_pspec(p, self.mesh) for p in self._outer_params]
        if self._group_cols:
            from paddle_tpu.parallel.scan_layers import stack_layer_vals

            n_layers = len(self._group_cols[0])
            packed_vals.extend(stack_layer_vals(
                [[col[l]._value for col in self._group_cols]
                 for l in range(n_layers)]))
            packed_specs.extend(
                PartitionSpec(None, *_param_pspec(col[0], self.mesh))
                for col in self._group_cols)
        self._zero3_scan_info = None
        if (zero_axis is not None and self.mesh is not None
                and zero_axis not in self.mesh.shape):
            import warnings

            # a typo'd axis must not silently train replicated at Z x the
            # provisioned parameter memory (axes of SIZE 1 stay silent —
            # build_mesh keeps them so specs are uniform across configs)
            warnings.warn(
                f"zero_axis={zero_axis!r} is not a mesh axis "
                f"({tuple(self.mesh.shape)}); ZeRO sharding is OFF")
        if zero_stage >= 3:
            n_outer = len(self._outer_params)
            packed_specs[:n_outer] = [
                _zero3_param_spec(s, v, zero_axis, self.mesh)
                for s, v in zip(packed_specs[:n_outer], packed_vals[:n_outer])
            ]
            if self._group_cols:
                # stacked columns persist reduce-scattered; the scan loop
                # re-gathers them per layer (gather-ahead by default) instead
                # of leaving the layout to GSPMD — see scan_layers.ScanShardInfo
                from paddle_tpu.parallel.scan_layers import ScanShardInfo

                mode = (flag("zero3_gather") if zero3_gather is None
                        else str(zero3_gather))
                cols, any_sharded = [], False
                for i, spec in enumerate(packed_specs[n_outer:]):
                    sharded, did = _zero3_stacked_spec(
                        spec, packed_vals[n_outer + i], zero_axis, self.mesh)
                    any_sharded = any_sharded or did
                    packed_specs[n_outer + i] = sharded
                    cols.append((PartitionSpec(*tuple(sharded)[1:]),
                                 PartitionSpec(*tuple(spec)[1:])))
                if (not any_sharded and zero_axis is not None
                        and zero_axis in self.mesh.shape
                        and self.mesh.shape[zero_axis] > 1):
                    import warnings

                    warnings.warn(
                        f"zero_stage=3: no stacked column has a free dim "
                        f"divisible by {zero_axis!r} "
                        f"(size {self.mesh.shape[zero_axis]}); the scan "
                        f"stack persists REPLICATED")
                if any_sharded:
                    if self.remat_policy not in ("none", "full"):
                        raise ValueError(
                            f"zero_stage=3 sharded-weights scan re-gathers "
                            f"and recomputes each layer in backward (its own "
                            f"'full'-grade schedule); remat policy "
                            f"{self.remat_policy!r} cannot apply to the "
                            f"sharded stack — use remat='none'/'full', or "
                            f"zero_stage<=2.")
                    self._zero3_scan_info = ScanShardInfo(
                        self.mesh, cols, mode=mode,
                        axis=zero_axis or "sharding",
                        act_spec=self.batch_spec)
        if self.fp8_policy != "none" and self._zero3_scan_info is not None:
            raise ValueError(
                "fp8_policy cannot compose with the zero_stage=3 "
                "sharded-weights scan: its custom vjp owns the scan "
                "residuals/cotangents and cannot thread the delayed-scaling "
                "amax state. Use zero_stage<=2 (optimizer-state sharding) "
                "with fp8_policy, or fp8_policy='none' with zero_stage=3.")
        self._param_specs = packed_specs
        self._key = jax.random.key(seed)
        # resume from a loaded optimizer's step count: Adam-style bias
        # correction must continue at t, not restart at 1 with warm moments
        self._step_i = int(getattr(optimizer, "_step_count", 0) or 0)

        # materialize params (sharded) + optimizer state. Outer params are
        # re-pointed at the placed arrays (shared buffers, as before); the
        # per-layer split of stacked group columns is DEFERRED to explicit
        # sync_params_to_model() calls — slicing here would keep a second
        # full copy of every layer's weights resident for the whole run
        self._param_vals = []
        for v, spec in zip(packed_vals, self._param_specs):
            if self.mesh is not None:
                v = jax.device_put(v, NamedSharding(self.mesh, spec))
            self._param_vals.append(v)
        for p, v in zip(self._outer_params,
                        self._param_vals[:len(self._outer_params)]):
            p._set_value(v)

        self._opt_states = None
        self._state_shardings = None
        if optimizer is not None:
            self._opt_states = []
            self._state_shardings = []
            n_outer_p = len(self._outer_params)
            for i, (pv, spec, st) in enumerate(
                    zip(self._param_vals, self._param_specs,
                        self._resume_states(optimizer))):
                st_sh = {}
                for k, v in st.items():
                    sp = _state_pspec(spec, v, zero_axis, self.mesh,
                                      start_dim=1 if i >= n_outer_p else 0)
                    sh = None
                    if self.mesh is not None:
                        if self._offload:
                            sh = NamedSharding(self.mesh, sp, memory_kind="pinned_host")
                        else:
                            sh = NamedSharding(self.mesh, sp)
                        v = jax.device_put(v, sh)
                    st[k] = v
                    st_sh[k] = sh
                self._opt_states.append(st)
                self._state_shardings.append(st_sh)

        self._jitted = None
        self._dispatch = None
        self._program_cache_status: dict = {}
        self._donate = donate

    def _resume_states(self, optimizer):
        """Fresh per-packed-entry optimizer-state dicts: resumed from
        optimizer._state when a loaded checkpoint provides them (per-layer
        states are stacked for group columns; layers without a saved state
        get fresh moments individually, matching the unrolled path's
        per-param granularity), else freshly initialized."""
        existing = getattr(optimizer, "_state", {})
        n_outer = len(self._outer_params)
        for p, pv in zip(self._outer_params, self._param_vals[:n_outer]):
            p._set_value(pv)
            if p.stop_gradient:
                # frozen params (e.g. a LoRA-frozen base) never see the
                # update loop — keep no moments for them, so adapter
                # training's optimizer state is sized to the adapter
                yield {}
                continue
            yield dict(existing.get(id(p)) or optimizer._init_state(p))
        for col, sv in zip(self._group_cols, self._param_vals[n_outer:]):
            sts = [existing.get(id(p)) for p in col]
            if any(s is not None for s in sts):
                filled = [dict(s) if s is not None
                          else dict(optimizer._init_state(Tensor(sv[l])))
                          for l, s in enumerate(sts)]
                if len({frozenset(f) for f in filled}) == 1:
                    yield {k: jnp.stack([f[k] for f in filled])
                           for k in filled[0]}
                    continue
                import warnings

                warnings.warn(
                    "scan packing: per-layer optimizer states have "
                    "mismatched keys; reinitializing the stacked entry's "
                    "moments from zero")
            yield dict(optimizer._init_state(Tensor(sv)))

    # -- the pure step -------------------------------------------------------
    def _loss_of(self, param_vals, batch, key, fp8_states=None):
        counter = [0]

        def next_key():
            counter[0] += 1
            return jax.random.fold_in(key, counter[0])

        from contextlib import nullcontext

        from paddle_tpu.parallel.scan_layers import layer_execution

        n_outer = len(self._outer_params)
        stacked = list(param_vals[n_outer:]) if self._group_cols else None
        # cooperating models apply the policy per layer (embed/head/CE stay
        # outside every remat region); for others the context carries 'none'
        # and _step_fn wraps the whole loss in the legacy checkpoint region
        policy = self.remat_policy if self._layer_capable else "none"
        # delayed-scaling fp8: install the execute-mode session handing the
        # per-callsite amax states (tracers) out in discovery order. When
        # fp8_states is None (discovery itself, or fp8 off) no session is
        # installed here — discovery wraps this call in a record session.
        fp8_ctx = nullcontext()
        if self.fp8_policy != "none" and fp8_states is not None:
            from paddle_tpu.amp.fp8 import fp8_execution

            fp8_ctx = fp8_execution(self.fp8_policy, states=fp8_states,
                                    layout=self._fp8_layout,
                                    hist_len=self._fp8_hist_len)
        prev = fleet_rng._tls.active_key_fn
        fleet_rng._tls.active_key_fn = next_key
        try:
            with fp8_ctx:
                with layer_execution(policy, stacked,
                                     shard_info=self._zero3_scan_info):
                    if isinstance(batch, dict):
                        # named-batch protocol (packed batches: input_ids /
                        # labels / segment_ids / position_ids / ...): EVERY
                        # leaf is a model kwarg — labels included, so fused-
                        # head models compute the loss in-model — and
                        # `labels` also feeds loss_fn, preserving the
                        # (out, label) contract
                        out = functional_call(self.model,
                                              param_vals[:n_outer],
                                              (), kwargs=dict(batch),
                                              params=self._outer_params)
                        label = Tensor(batch["labels"])
                    else:
                        out = functional_call(self.model,
                                              param_vals[:n_outer],
                                              batch[:-1],
                                              params=self._outer_params)
                        label = Tensor(batch[-1])
                loss = self.loss_fn(out, label)
            return loss._value
        finally:
            fleet_rng._tls.active_key_fn = prev

    def _step_fn(self, param_vals, opt_states, batch, key, lr, step_i,
                 fp8_states=None, scaler_scale=None):
        fp8_on = self.fp8_policy != "none"
        fp8_in = list(fp8_states) if fp8_states is not None else []
        scaling = self._scaler is not None

        def run_loss(full_vals, fp8_s):
            return self._loss_of(full_vals, batch, key,
                                 fp8_states=fp8_s if fp8_on else None)

        if self.remat and not self._layer_capable:
            from paddle_tpu.parallel.scan_layers import remat_wrap

            # legacy whole-loss region for models that cannot scope remat
            # per layer themselves (the policy still applies, e.g. tagged
            # residuals offload under 'offload_residuals')
            run_loss = remat_wrap(run_loss, self.remat_policy)

        trainable_idx = [i for i, t in enumerate(self._trainable) if t]

        def moe_stats():
            # summed MoE stats over the layers' freshly-set in-trace
            # attributes (valid tracers of THIS forward)
            aux = jnp.zeros((), jnp.float32)
            dropped = jnp.zeros((), jnp.float32)
            for l in self._moe_layers:
                if l.l_aux is not None:
                    aux = aux + l.l_aux._value.astype(jnp.float32)
                if l.tokens_dropped is not None:
                    dropped = (dropped
                               + l.tokens_dropped._value.astype(jnp.float32))
            return jnp.stack([aux, dropped])

        def loss_all(train_vals, fp8_s):
            full = list(param_vals)
            for i, v in zip(trainable_idx, train_vals):
                full[i] = v
            loss = run_loss(full, fp8_s)
            moe_vec = moe_stats() if self._moe_layers else None
            # float16 loss scaling happens INSIDE the differentiated fn so
            # the whole backward benefits; the aux output reports the
            # unscaled loss
            if scaling:
                return loss * scaler_scale.astype(loss.dtype), (loss,
                                                                moe_vec)
            return loss, (loss, moe_vec)

        train_vals = [param_vals[i] for i in trainable_idx]
        # the gradient of the loss w.r.t. the fp8 amax histories IS their
        # updated value (the fp8_dot custom-vjp's state-as-gradient
        # contract), so new_fp8 below is next step's state pytree
        (_, (loss, moe_vec)), (grads, new_fp8) = jax.value_and_grad(
            loss_all, argnums=(0, 1), has_aux=True)(train_vals, fp8_in)

        found_inf = None
        if scaling:
            inv = (1.0 / scaler_scale).astype(jnp.float32)
            unscaled = []
            bad = jnp.zeros((), jnp.bool_)
            for g in grads:
                g32 = g.astype(jnp.float32) * inv
                bad = bad | ~jnp.isfinite(g32).all()
                unscaled.append(g32.astype(g.dtype))
            grads = unscaled
            found_inf = bad
        if self._anomaly:
            # the per-step HEALTH scalar (docs/resilience.md), riding the
            # found_inf convention: non-finite loss or ANY non-finite grad
            # marks the step unhealthy — the update below is skipped (a NaN
            # batch can never poison the params) and the scalar settles on
            # the host lazily, feeding the AnomalyDetector
            bad = (found_inf if found_inf is not None
                   else jnp.zeros((), jnp.bool_))
            if not scaling:
                for g in grads:
                    bad = bad | ~jnp.isfinite(g).all()
            found_inf = bad | ~jnp.isfinite(loss)
        if fp8_on and found_inf is not None:
            # a skipped step must not poison the amax histories: the
            # backward observed inf/nan amaxes, and delayed_scale of an
            # inf history is 0 -> NaN gradients on the NEXT step. Keep
            # the previous state, mirroring the params/moments skip.
            new_fp8 = jax.tree_util.tree_map(
                lambda old, new: jnp.where(found_inf, old, new),
                fp8_in, list(new_fp8))

        step_metrics = None
        if self._telemetry:
            # the honest per-step side output: tiny fp32 scalars riding the
            # program's outputs (no second dispatch, no host sync — readers
            # settle them lazily via settle_metrics), PACKED into one
            # [len(metric_keys)] vector so the host pays a single readback
            # per step, not one per metric. grad_norm is the GLOBAL norm
            # over every trainable leaf, post-unscale.
            gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                              for g in grads))
            parts = [
                loss.astype(jnp.float32),
                gn,
                (found_inf.astype(jnp.float32) if found_inf is not None
                 else jnp.zeros((), jnp.float32)),
            ]
            if fp8_on:
                leaves = jax.tree_util.tree_leaves(new_fp8)
                parts.append(
                    jnp.max(jnp.stack([jnp.max(l) for l in leaves]))
                    if leaves else jnp.zeros((), jnp.float32))
            if self._moe_layers:
                parts.extend([moe_vec[0], moe_vec[1]])
            step_metrics = jnp.stack(parts)
        new_params = list(param_vals)
        new_states = list(opt_states) if opt_states is not None else None
        if self.optimizer is not None:
            offload = self._offload and self._state_shardings is not None

            def one_update(j, i, st):
                g = grads[j]
                if g.dtype != param_vals[i].dtype:
                    g = g.astype(param_vals[i].dtype)
                return self.optimizer._update(param_vals[i], g, st, lr,
                                              step_i)

            def streamed_state(i):
                st = opt_states[i]
                if offload:
                    # states live in pinned host memory; stream to HBM for
                    # the update (out_shardings stream the results back) —
                    # the reference's offload variants do the same H2D/D2H
                    # per step
                    st = {k: jax.device_put(v, self._state_shardings[i][k]
                                            .with_memory_kind("device"))
                          for k, v in st.items()}
                return st

            for j, i in enumerate(trainable_idx):
                st = streamed_state(i)
                np_, ns_ = one_update(j, i, st)
                if found_inf is not None:
                    # inf/nan grads (or an unhealthy anomaly-detected step)
                    # skip the WHOLE update: params and moments keep their
                    # previous values (GradScaler inf-skip semantics under
                    # jit). Per-tensor select, NOT one lax.cond around the
                    # loop: XLA fuses the select into the update kernel's
                    # epilogue (measured noise-level overhead), whereas the
                    # conditional's operand boundary materializes/copies
                    # every captured param+moment (measured ~10%/step).
                    np_ = jnp.where(found_inf, param_vals[i], np_)
                    ns_ = {k: jnp.where(found_inf, st[k], v)
                           for k, v in ns_.items()}
                new_params[i] = np_
                new_states[i] = ns_
        if fp8_on or scaling or self._anomaly:
            flag_out = (found_inf.astype(jnp.float32) if found_inf is not None
                        else jnp.zeros((), jnp.float32))
            if step_metrics is not None:
                return (loss, new_params, new_states, list(new_fp8),
                        flag_out, step_metrics)
            return loss, new_params, new_states, list(new_fp8), flag_out
        if step_metrics is not None:
            return loss, new_params, new_states, step_metrics
        return loss, new_params, new_states

    def _build(self):
        mesh = self.mesh
        extended = (self.fp8_policy != "none" or self._scaler is not None
                    or self._anomaly)
        if mesh is not None and self.optimizer is not None:
            pshard = [NamedSharding(mesh, s) for s in self._param_specs]
            sshard = self._state_shardings
            repl = NamedSharding(mesh, PartitionSpec())
            # the telemetry side output is ONE packed fp32 vector — always
            # replicated (its layout is static per configuration)
            mshard = repl if self._telemetry else None
            if extended:
                # amax histories are tiny ([H] / [L, H]) — they ride
                # replicated next to their (possibly sharded) stack column
                fshard = jax.tree_util.tree_map(
                    lambda _: repl, self._fp8_states or [])
                outs = (repl, pshard, sshard, fshard, repl)
                if mshard is not None:
                    outs = outs + (mshard,)
                self._jitted = jax.jit(
                    self._step_fn,
                    in_shardings=(pshard, sshard, None, None, None, None,
                                  fshard, None),
                    out_shardings=outs,
                    donate_argnums=(0, 1, 6) if self._donate else (),
                )
            else:
                outs = (repl, pshard, sshard)
                if mshard is not None:
                    outs = outs + (mshard,)
                self._jitted = jax.jit(
                    self._step_fn,
                    in_shardings=(pshard, sshard, None, None, None, None),
                    out_shardings=outs,
                    donate_argnums=(0, 1) if self._donate else (),
                )
        else:
            donate = (((0, 1, 6) if extended else (0, 1))
                      if self._donate else ())
            self._jitted = jax.jit(self._step_fn, donate_argnums=donate)
        # persistent AOT program cache (FLAGS_program_cache_dir): the first
        # real dispatch lowers and LOADS yesterday's executable instead of
        # recompiling — the cold-trainer time-to-first-step path of
        # docs/autotuning.md. Off (the default) this is self._jitted.
        from paddle_tpu.tuning.program_cache import AotProgram, process_cache

        if process_cache() is not None:
            self._dispatch = AotProgram(self._jitted, "train_step",
                                        self._program_cache_status)
        else:
            self._dispatch = self._jitted

    @property
    def program_cache(self) -> dict:
        """{'status': hit|miss, 'ms': ...} of this step's AOT program-cache
        resolution; {} when the cache is off or nothing dispatched yet."""
        return dict(self._program_cache_status.get("train_step", {}))

    # -- public --------------------------------------------------------------
    def __call__(self, *batch):
        """batch: (*inputs, label) as Tensors/arrays, OR one dict (the
        named-batch protocol a packed loader emits: every entry becomes a
        model kwarg — `labels` is required and also feeds loss_fn). Extra
        leaves like segment_ids/position_ids therefore ride along without
        positional-order coupling, get the same cached trimmed shardings as
        input_ids, and never retrace the step (the jit key is the batch
        pytree structure, stable across steps). Returns the loss as an
        UN-FETCHED Tensor: reading it (float()) is the device->host sync, so
        callers control how often dispatch is broken (`metrics_every`).
        Pre-placed inputs (a DeviceFeeder batch) whose sharding already
        matches skip the device_put entirely."""
        from paddle_tpu.profiler import RecordEvent

        named = len(batch) == 1 and isinstance(batch[0], dict)
        if named and "labels" not in batch[0]:
            raise ValueError(
                "a dict batch must carry a 'labels' entry (it feeds both "
                f"the model and loss_fn); got keys {sorted(batch[0])}")
        with RecordEvent("CompiledTrainStep::place"):
            if named:
                keys = sorted(batch[0])
                flat, moved = self._spec_cache.place(
                    [batch[0][k] for k in keys])
                vals = dict(zip(keys, flat))
            else:
                vals, moved = self._spec_cache.place(batch)
            self.h2d_transfers += moved
        if self._jitted is None:
            if self.fp8_policy != "none" and self._fp8_states is None:
                self._discover_fp8(vals)
            self._build()
        self._step_i += 1
        self._key, sub = jax.random.split(self._key)
        lr = jnp.asarray(
            self.optimizer.get_lr() if self.optimizer is not None else 0.0, jnp.float32
        )
        if faults.fire_check("step.grads"):
            # chaos: poison THIS step — NaN grads via the first float batch
            # leaf, or (integer-only batches) a NaN lr corrupting the params
            vals, leaf_poisoned = _nan_poison(vals)
            if not leaf_poisoned:
                lr = jnp.asarray(float("nan"), jnp.float32)
        extended = (self.fp8_policy != "none" or self._scaler is not None
                    or self._anomaly)
        with RecordEvent("CompiledTrainStep::dispatch",
                         attrs={"step": self._step_i}):
            if extended:
                scale_arr = jnp.asarray(
                    self._scaler._scale if self._scaler is not None else 1.0,
                    jnp.float32)
                args = (self._param_vals, self._opt_states, vals, sub, lr,
                        jnp.asarray(self._step_i, jnp.int32),
                        self._fp8_states if self._fp8_states is not None
                        else [],
                        scale_arr)
            else:
                args = (self._param_vals, self._opt_states, vals, sub, lr,
                        jnp.asarray(self._step_i, jnp.int32))
            if self._abstract_args is None:
                # abstract (shape, dtype, sharding) mirror of the step's
                # arguments — what cost_analysis() lowers against later
                # (the concrete arrays are about to be donated)
                self._abstract_args = jax.tree_util.tree_map(
                    _abstractify, args)
            outs = self._dispatch(*args)
            step_metrics = None
            if self._telemetry:
                step_metrics = outs[-1]
                outs = outs[:-1]
            if extended:
                (loss, self._param_vals, self._opt_states, new_fp8,
                 found) = outs
                if self.fp8_policy != "none":
                    self._fp8_states = new_fp8
                if self._scaler is not None:
                    # settle the scaler state machine lazily: flags are read
                    # only once their device value is ready, so async
                    # dispatch never blocks here (drain() settles the rest)
                    self._pending_inf.append(found)
                    self._settle_scaler(block=False)
                if self._anomaly:
                    # same lazy contract for the health scalar: the detector
                    # only sees READY values, so step_async run-ahead is
                    # never broken by detection
                    self._pending_health.append((self._step_i, loss, found))
                    self.settle_anomalies(block=False)
            else:
                loss, self._param_vals, self._opt_states = outs
            if step_metrics is not None:
                # same lazy contract as health/found_inf: the dict's device
                # scalars settle once ready (drain() settles all); the wall
                # time stamps host-side dispatch pacing
                import time as _time

                self._pending_metrics.append(
                    (self._step_i, step_metrics, _time.perf_counter()))
                self.settle_metrics(block=False)
        # bounded run-ahead: block on the loss of step N-window before
        # returning, so at most `window` compiled steps are queued on-device
        self._window.admit(loss)
        if self.optimizer is not None:
            _innermost_opt(self.optimizer)._step_count = self._step_i
            if hasattr(self.optimizer._lr, "step") and not isinstance(self.optimizer._lr, float):
                pass  # schedulers stepped by caller, matching eager semantics
        return Tensor(loss)

    def step_async(self, *batch):
        """Dispatch one step and return a LossFuture — the deferred-read
        handle for run-ahead training loops. Every `metrics_every`-th call
        blocks until its step finishes before returning (so the caller's
        periodic float() is free); with metrics_every=0 nothing ever blocks
        here and run-ahead is bounded only by the dispatch window.
        `drain()` before checkpointing/timing."""
        from paddle_tpu.io.device_feed import LossFuture

        f = LossFuture(self(*batch))
        self._async_count += 1
        if self.metrics_every and self._async_count % self.metrics_every == 0:
            f.block()
        return f

    def drain(self):
        """Block until every dispatched step has executed (and, with a
        grad_scaler / anomaly detector / telemetry, fold every outstanding
        found_inf, health flag and metrics pytree into their consumers)."""
        self._window.drain()
        if self._scaler is not None:
            self._settle_scaler(block=True)
        if self._anomaly:
            self.settle_anomalies(block=True)
        if self._telemetry:
            self.settle_metrics(block=True)

    # -- honest step telemetry (docs/observability.md) -----------------------
    def settle_metrics(self, block: bool = False):
        """Fold finished steps' metrics side-pytrees into `last_metrics`,
        in dispatch order. block=False only consumes values whose buffers
        are already ready — the non-blocking path runs after every
        dispatch, so step_async run-ahead is never broken by telemetry."""
        while self._pending_metrics:
            step_i, md, wall = self._pending_metrics[0]
            if not block:
                ready = getattr(md, "is_ready", None)
                if ready is not None and not ready():
                    break
            self._pending_metrics.pop(0)
            vals = np.asarray(md)  # ONE readback for the whole vector
            rec = dict(zip(self._metric_keys, (float(v) for v in vals)))
            rec["step"] = step_i
            # host-side pacing: wall time between consecutive dispatches
            # (the end-to-end step time a training loop actually feels,
            # input pipeline included — distinct from device step time)
            if self._prev_metric_wall is not None:
                rec["host_step_ms"] = round(
                    (wall - self._prev_metric_wall) * 1e3, 3)
            self._prev_metric_wall = wall
            self._last_metrics = rec

    def last_metrics(self) -> dict | None:
        """The most recent SETTLED step's telemetry: {step, loss,
        grad_norm, skipped[, fp8_amax_max][, host_step_ms]} — None before
        the first settled step or with telemetry off."""
        if self._telemetry:
            self.settle_metrics(block=False)
        return self._last_metrics

    @property
    def collects_metrics(self) -> bool:
        return self._telemetry

    def cost_analysis(self) -> dict:
        """XLA's own cost model for ONE compiled step (flops, bytes
        accessed, ...) — the honest FLOP count MFU derives from, replacing
        hand-counted formulas. Lowers + compiles a second AOT executable
        from the captured abstract arguments (one-off, cached; call OFF
        the hot path). Needs at least one executed step."""
        if self._cost_analysis_cache is not None:
            return self._cost_analysis_cache
        if self._jitted is None or self._abstract_args is None:
            raise RuntimeError(
                "cost_analysis() needs at least one executed step (the "
                "abstract argument signature is captured at first dispatch)")
        # an AOT-cached dispatch already holds the compiled step — reuse it
        # instead of lowering/compiling a second executable
        compiled = getattr(self._dispatch, "_compiled", None)
        if compiled is None:
            compiled = self._jitted.lower(*self._abstract_args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        self._cost_analysis_cache = dict(ca)
        return self._cost_analysis_cache

    def flops_per_step(self) -> float:
        """Total XLA-reported FLOPs of one step program (0.0 when the
        backend does not report them)."""
        return float(self.cost_analysis().get("flops", 0.0) or 0.0)

    # -- anomaly detection ---------------------------------------------------
    @property
    def anomaly_detector(self):
        return self._anomaly_det

    def settle_anomalies(self, block: bool = False):
        """Feed the AnomalyDetector from finished steps' device health
        scalars, in dispatch order. block=False only consumes values whose
        buffers are already ready — the non-blocking path __call__ runs
        after every dispatch; drain() settles the rest."""
        if self._anomaly_det is None:
            return
        while self._pending_health:
            step_i, loss, health = self._pending_health[0]
            if not block:
                ready = getattr(health, "is_ready", None)
                if ready is not None and not ready():
                    break
            self._pending_health.pop(0)
            self._anomaly_det.observe(step_i, float(loss), float(health))

    # -- fp8 delayed-scaling state -------------------------------------------
    def _discover_fp8(self, vals):
        """One abstract trace (jax.eval_shape — no compile, no FLOPs) of the
        loss under a recording fp8 session: counts the matmul callsites in
        call order, noting which sit inside the scanned layer group, and
        allocates the amax-history pytree — [H] per plain callsite, [L, H]
        per scanned one — placed replicated on the mesh."""
        from paddle_tpu.amp import fp8 as _fp8

        holder = {}

        def probe(pv, batch, key):
            with _fp8.fp8_recording(self.fp8_policy,
                                    self._fp8_hist_len) as rec:
                holder["rec"] = rec
                return self._loss_of(pv, batch, key)

        jax.eval_shape(probe, self._param_vals, vals, jax.random.key(0))
        rec = holder["rec"]
        self._fp8_layout = list(rec.layout)
        states = rec.init_states()
        if self.mesh is not None:
            repl = NamedSharding(self.mesh, PartitionSpec())
            states = jax.tree_util.tree_map(
                lambda v: jax.device_put(v, repl), states)
        self._fp8_states = states

    def fp8_state_dict(self):
        """The delayed-scaling amax state for checkpointing: the callsite
        layout plus the history arrays (host numpy). None before the first
        step has discovered the layout (or with fp8 off)."""
        if self._fp8_states is None:
            return None
        return {"layout": [tuple(e) for e in self._fp8_layout],
                "states": jax.tree_util.tree_map(
                    lambda v: np.asarray(v), self._fp8_states)}

    def load_fp8_state(self, snap):
        """Restore a fp8_state_dict() snapshot (before or after the first
        step); resuming then continues the uninterrupted amax trajectory."""
        if snap is None:
            return
        self._fp8_layout = [tuple(e) for e in snap["layout"]]
        states = snap["states"]
        if self.mesh is not None:
            repl = NamedSharding(self.mesh, PartitionSpec())
            states = jax.tree_util.tree_map(
                lambda v: jax.device_put(jnp.asarray(v), repl), states)
        else:
            states = jax.tree_util.tree_map(jnp.asarray, states)
        self._fp8_states = states

    def _settle_scaler(self, block: bool):
        """Advance the GradScaler state machine from finished steps' device
        found_inf flags, in dispatch order. block=False only consumes flags
        whose value is already on host-reachable (ready) buffers."""
        while self._pending_inf:
            f = self._pending_inf[0]
            if not block:
                ready = getattr(f, "is_ready", None)
                if ready is not None and not ready():
                    break
            self._pending_inf.pop(0)
            self._scaler._found_inf = bool(float(f) > 0.0)
            self._scaler.update()

    def sync_params_to_model(self):
        """Write the current device arrays back into the model's Tensors
        (checkpointing / eval interop). Scan-packed group columns are split
        back per layer, so state_dict layout is identical with scan on/off."""
        n_outer = len(self._outer_params)
        for p, v in zip(self._outer_params, self._param_vals[:n_outer]):
            p._set_value(v)
        for col, sv in zip(self._group_cols, self._param_vals[n_outer:]):
            for l, p in enumerate(col):
                p._set_value(sv[l])

    def sync_states_to_optimizer(self):
        """Write the in-program optimizer state back into optimizer._state so
        optimizer.state_dict() reflects trained moments (checkpoint parity).
        Targets the INNERMOST optimizer: wrappers delegate state_dict() there,
        and attribute assignment on a wrapper would only shadow it. Stacked
        group-column states are split back into per-layer entries."""
        if self.optimizer is None or self._opt_states is None:
            return
        opt = _innermost_opt(self.optimizer)
        n_outer = len(self._outer_params)
        for p, st in zip(self._outer_params, self._opt_states[:n_outer]):
            if not st:       # frozen param: no moments were ever allocated
                continue
            opt._state[id(p)] = dict(st)
        for col, st in zip(self._group_cols, self._opt_states[n_outer:]):
            for l, p in enumerate(col):
                opt._state[id(p)] = {k: v[l] for k, v in st.items()}
        opt._step_count = self._step_i

    # -- elastic checkpoint interface ----------------------------------------
    def _live_param_map(self):
        """id(parameter) -> its CURRENT device array. Group-column entries
        are lazy slices of the stacked [L, ...] arrays (async dispatch, no
        host sync); model buffers are not included (their Tensors are live)."""
        live = {}
        n_outer = len(self._outer_params)
        for p, v in zip(self._outer_params, self._param_vals[:n_outer]):
            live[id(p)] = v
        for col, sv in zip(self._group_cols, self._param_vals[n_outer:]):
            for l, p in enumerate(col):
                live[id(p)] = sv[l]
        return live

    def named_train_state(self):
        """(arrays, meta) for elastic checkpointing — the full training state
        under MESH-AGNOSTIC names, without a single host sync:

        * ``model/<state-dict name>`` — every model param (split per layer
          from the scan stack, so scan on/off saves look identical) + buffer,
          as live device arrays;
        * ``opt/<state-dict name>/<slot>`` — optimizer moments keyed by the
          owning parameter's NAME (not its position), so a pipeline runtime
          with a different parameter order resumes the same moments;
        * ``rng/key`` — the step's PRNG key data (the dropout trajectory
          continues bit-exactly across a resume);
        * meta: step count, fp8 callsite layout (+ ``fp8/<i>/<slot>`` amax
          histories in arrays), GradScaler scalars.

        The returned arrays may still be computing and WILL be invalidated by
        the next step's buffer donation — `checkpoint.elastic.capture` makes
        donation-safe device copies before the writer thread reads them.
        GradScaler scalars reflect the last SETTLED step (drain() first for
        exactness — the documented async-AMP lag)."""
        live = self._live_param_map()
        id2name = {}
        arrays = {}
        for name, t in self.model.state_dict().items():
            arrays[f"model/{name}"] = live.get(id(t), t._value)
            id2name[id(t)] = name
        if self._opt_states is not None:
            n_outer = len(self._outer_params)
            for p, st in zip(self._outer_params, self._opt_states[:n_outer]):
                name = id2name.get(id(p))
                if name is None:
                    continue
                for k, v in st.items():
                    arrays[f"opt/{name}/{k}"] = v
            for col, st in zip(self._group_cols,
                               self._opt_states[n_outer:]):
                for l, p in enumerate(col):
                    name = id2name.get(id(p))
                    if name is None:
                        continue
                    for k, v in st.items():
                        arrays[f"opt/{name}/{k}"] = v[l]
        arrays["rng/key"] = jax.random.key_data(self._key)
        meta = {"step": int(self._step_i)}
        if self._fp8_states is not None:
            meta["fp8_layout"] = [list(e) for e in self._fp8_layout]
            flat = jax.tree_util.tree_leaves(self._fp8_states)
            meta["fp8_leaves"] = len(flat)
            for i, leaf in enumerate(flat):
                arrays[f"fp8/{i:05d}"] = leaf
        if self._scaler is not None:
            meta["scaler"] = dict(self._scaler.state_dict())
        return arrays, meta

    def load_resume_extras(self, arrays, meta):
        """Restore the per-step extras a plain (model, optimizer) state-dict
        load cannot carry: RNG key, step counter, fp8 amax histories, and
        GradScaler scalars. Params/moments flow through
        `checkpoint.elastic.restore` BEFORE constructing the step (the
        constructor re-shards them for the target mesh)."""
        if "rng/key" in arrays:
            self._key = jax.random.wrap_key_data(
                jnp.asarray(np.asarray(arrays["rng/key"])))
        if "step" in meta:
            self._step_i = int(meta["step"])
            if self.optimizer is not None:
                _innermost_opt(self.optimizer)._step_count = self._step_i
        if meta.get("fp8_layout") is not None and self.fp8_policy != "none":
            n = int(meta.get("fp8_leaves", 0))
            leaves = [np.asarray(arrays[f"fp8/{i:05d}"]) for i in range(n)]
            # rebuild the callsite-state pytree: layout entries expand to one
            # {x,w,g} dict per callsite (scan entries carry k callsites)
            from paddle_tpu.amp.fp8 import STATE_KEYS

            # tree_leaves flattened each callsite dict in sorted-key order;
            # rebuild with the same ordering
            states, it = [], iter(leaves)
            for e in meta["fp8_layout"]:
                count = 1 if e[0] == "plain" else int(e[2])
                for _ in range(count):
                    states.append({k: next(it) for k in sorted(STATE_KEYS)})
            self.load_fp8_state({"layout": [tuple(e) for e in
                                            meta["fp8_layout"]],
                                 "states": states})
        if meta.get("scaler") is not None and self._scaler is not None:
            self._scaler.load_state_dict(dict(meta["scaler"]))

    @property
    def step_count(self):
        return self._step_i
