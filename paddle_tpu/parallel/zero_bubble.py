"""Executable ZB-H1 zero-bubble pipeline schedule.

Reference parity: pipeline_zero_bubble.py (distributed/passes/
pipeline_scheduler_pass/) executes {F, B, W} job lists per rank, where the
backward is split into B (activation grad — on the inter-stage critical path)
and W (weight grad — no downstream consumer, fills the drain bubble).

TPU-native design: the zb_h1_schedule tick TABLE (pipeline_schedules.py) is
compiled into ONE XLA program — a lax.scan over ticks inside shard_map over
the 'pp' axis. Each tick every rank dispatches its scheduled op through
lax.switch (idle/F/B/W branches are collective-free; the two ppermutes — one
forward activation hop, one backward cotangent hop — run unconditionally
every tick, so SPMD ranks never diverge on collectives). Microbatch-keyed
stashes carry (stage input, arriving cotangent) between F, B and W ticks;
their capacities are computed statically from the table (max live window).

Cost accounting (honest): B and W each re-run the stage forward (vjp-based
split — the same recompute a remat'd 1F1B backward performs once), so one
microbatch costs F + (F+Bx) + (F+Bw) FLOPs vs remat-1F1B's F + (F+Bx+Bw):
one extra forward per microbatch buys the bubble reduction. The parity test
checks grads match the dense model exactly; the probe measures the idle
(bubble) fraction against the compiled 1F1B runtime's.
"""
from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.mesh import get_mesh
from paddle_tpu.distributed.mesh import shard_map_compat as _shard_map
from paddle_tpu.parallel.pipeline_schedules import zb_h1_schedule
from paddle_tpu.parallel.train_step import functional_call

__all__ = ["ZBH1PipelinedStep"]

_OP = {"F": 1, "B": 2, "W": 3}


def _tables(sched, S):
    """numpy per-tick tables from a schedule dict: op/mb codes plus the
    arrival tables (what lands on each rank at the START of tick t = what its
    neighbor sent at t-1)."""
    ticks = sched["ticks"]
    T = len(ticks)
    op = np.zeros((T, S), np.int32)
    mb = np.zeros((T, S), np.int32)
    for t, row in enumerate(ticks):
        for r, cell in enumerate(row):
            if cell is not None:
                op[t, r] = _OP[cell[0]]
                mb[t, r] = cell[1]
    arr_f_valid = np.zeros((T, S), bool)
    arr_f_mb = np.zeros((T, S), np.int32)
    arr_b_valid = np.zeros((T, S), bool)
    arr_b_mb = np.zeros((T, S), np.int32)
    for t in range(1, T):
        for r in range(S):
            if r > 0 and op[t - 1, r - 1] == _OP["F"]:
                arr_f_valid[t, r] = True
                arr_f_mb[t, r] = mb[t - 1, r - 1]
            if r < S - 1 and op[t - 1, r + 1] == _OP["B"]:
                arr_b_valid[t, r] = True
                arr_b_mb[t, r] = mb[t - 1, r + 1]
    return op, mb, arr_f_valid, arr_f_mb, arr_b_valid, arr_b_mb


def _stash_capacity(sched, S, M):
    """Max (next_f - next_w) span over the run: microbatch slots live from
    first touch until their W completes, and per-rank F/B/W are monotone in
    mb, so mb %% cap is collision-free when cap covers the widest window."""
    done = {k: [[-1] * M for _ in range(S)] for k in "FBW"}
    span = 1
    prog = {k: [0] * S for k in "FBW"}
    for row in sched["ticks"]:
        for r, cell in enumerate(row):
            if cell is not None:
                kind, m, _ = cell
                done[kind][r][m] = 1
                prog[kind][r] = m + 1
        for r in range(S):
            span = max(span, prog["F"][r] - prog["W"][r])
    return span + 1


class ZBH1PipelinedStep:
    """ZB-H1 for (embed, blocks, head) models on a pp-only mesh.

    run(ids, labels) -> (loss, (embed_grads, stacked_block_grads, head_grads))
    with grads numerically equal to the dense model's (parity-tested).
    ids/labels: [M * mb_size, seq]-style arrays split into M microbatches on
    the leading dim."""

    def __init__(self, embed_layer, blocks: Sequence, head_layer,
                 loss_fn: Callable, mesh: Mesh | None = None,
                 num_micro: int = 2, seed: int = 0, optimizer=None):
        self.mesh = mesh if mesh is not None else get_mesh()
        if self.mesh is None or "pp" not in self.mesh.shape:
            raise ValueError("ZBH1PipelinedStep requires a mesh with a 'pp' axis")
        self.S = int(self.mesh.shape["pp"])
        if len(blocks) % self.S != 0:
            raise ValueError(f"{len(blocks)} blocks not divisible by pp={self.S}")
        self.bps = len(blocks) // self.S
        self.M = int(num_micro)
        self.embed = embed_layer
        self.blocks = list(blocks)
        self.head = head_layer
        self.loss_fn = loss_fn
        self._key = jax.random.key(seed)

        self.sched = zb_h1_schedule(self.S, self.M)
        (self._op, self._mb, self._afv, self._afm, self._abv,
         self._abm) = _tables(self.sched, self.S)
        self.T = len(self.sched["ticks"])
        self.cap = _stash_capacity(self.sched, self.S, self.M)

        mesh = self.mesh
        self._embed_params = embed_layer.parameters()
        self._head_params = head_layer.parameters()
        self._block_params = [b.parameters() for b in blocks]
        nb = len(self._block_params[0])
        stacked = []
        for i in range(nb):
            vals = [bp[i]._value for bp in self._block_params]
            stacked.append(jnp.stack(vals).reshape(
                (self.S, self.bps) + vals[0].shape))
        self._block_specs = [
            PartitionSpec("pp", *([None] * (a.ndim - 1))) for a in stacked]
        self._stacked_blocks = [
            jax.device_put(a, NamedSharding(mesh, s))
            for a, s in zip(stacked, self._block_specs)]
        self._embed_vals = [jax.device_put(p._value, NamedSharding(mesh, PartitionSpec()))
                            for p in self._embed_params]
        self._head_vals = [jax.device_put(p._value, NamedSharding(mesh, PartitionSpec()))
                           for p in self._head_params]
        self._jitted = None

        # optional optimizer: ZB-H1 as a full Fleet train-batch mode
        self.optimizer = optimizer
        self._opt_states = None
        self._update_jit = None
        # resume parity: continue from a restored optimizer's step count
        from paddle_tpu.parallel.train_step import _innermost_opt

        self._step_i = (int(getattr(_innermost_opt(optimizer), "_step_count",
                                    0) or 0) if optimizer is not None else 0)
        if optimizer is not None:
            from paddle_tpu.parallel.train_step import init_opt_states

            self._opt_states = init_opt_states(
                optimizer,
                self._embed_vals + self._stacked_blocks + self._head_vals)

    # -- pure per-rank compute pieces ---------------------------------------

    def _stage_fwd(self, stage_params, x):
        def one_block(h, layer_params):
            out = functional_call(self.blocks[0], layer_params, (Tensor(h),))
            return out._value if isinstance(out, Tensor) else out, None

        h, _ = jax.lax.scan(one_block, x, stage_params)
        return h

    def _embed_fwd(self, embed_vals, ids_mb):
        out = functional_call(self.embed, embed_vals, (Tensor(ids_mb),))
        return out._value if isinstance(out, Tensor) else out

    def _last_chain(self, stage_params, head_vals, x, labels_mb):
        """loss(head(stage(x))) for the last rank."""
        y = self._stage_fwd(stage_params, x)
        h = functional_call(self.head, head_vals, (Tensor(y),))
        hv = h._value if isinstance(h, Tensor) else h
        loss = self.loss_fn(Tensor(hv), Tensor(labels_mb))
        return (loss._value if isinstance(loss, Tensor) else loss).astype(jnp.float32)

    # -- the compiled schedule ----------------------------------------------

    def _build(self, mb_shape, ids_dtype):
        mesh, S, M, T, cap = self.mesh, self.S, self.M, self.T, self.cap
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]

        op_t = jnp.asarray(self._op)
        mb_t = jnp.asarray(self._mb)
        afv_t = jnp.asarray(self._afv)
        afm_t = jnp.asarray(self._afm)
        abv_t = jnp.asarray(self._abv)
        abm_t = jnp.asarray(self._abm)

        def body(stacked_local, embed_vals, head_vals, ids_mb, labels_mb):
            rank = jax.lax.axis_index("pp")
            stage_params = [a[0] for a in stacked_local]
            act_shape = mb_shape  # stage in/out share the shape (residual nets)

            zero_act = jnp.zeros(act_shape, jnp.float32)
            state = dict(
                instash=jnp.zeros((cap,) + act_shape, jnp.float32),
                dystash=jnp.zeros((cap,) + act_shape, jnp.float32),
                out_f=zero_act,
                out_b=zero_act,
                fwd_in=zero_act,
                bwd_in=zero_act,
                g_stage=[jnp.zeros_like(p) for p in stage_params],
                g_embed=[jnp.zeros_like(v) for v in embed_vals],
                g_head=[jnp.zeros_like(v) for v in head_vals],
                loss=jnp.zeros((), jnp.float32),
            )

            def set_slot(buf, m, val):
                return jax.lax.dynamic_update_index_in_dim(
                    buf, val, m % cap, 0)

            def get_slot(buf, m):
                return jax.lax.dynamic_index_in_dim(buf, m % cap, 0,
                                                    keepdims=False)

            def idle_br(state, m):
                return state

            def f_br(state, m):
                x = jnp.where(rank == 0,
                              self._embed_fwd(embed_vals, ids_mb[m]),
                              get_slot(state["instash"], m))
                y = self._stage_fwd(stage_params, x)
                st = dict(state)
                st["instash"] = set_slot(state["instash"], m, x)
                st["out_f"] = y
                return st

            def b_br(state, m):
                x = get_slot(state["instash"], m)
                dy = get_slot(state["dystash"], m)

                def last_case(_):
                    # cotangent 1/M: run() reports the MEAN microbatch loss
                    lval, vjp = jax.vjp(
                        lambda xx: self._last_chain(stage_params, head_vals,
                                                    xx, labels_mb[m]), x)
                    (dx,) = vjp(jnp.asarray(1.0 / M, jnp.float32))
                    return dx, lval

                def mid_case(_):
                    _, vjp = jax.vjp(
                        lambda xx: self._stage_fwd(stage_params, xx), x)
                    (dx,) = vjp(dy)
                    return dx, jnp.zeros((), jnp.float32)

                dx, lval = jax.lax.cond(rank == S - 1, last_case, mid_case,
                                        None)

                def embed_case(_):
                    _, evjp = jax.vjp(
                        lambda ev: self._embed_fwd(ev, ids_mb[m]), embed_vals)
                    (ge,) = evjp(dx)
                    return list(ge)

                def no_embed(_):
                    return [jnp.zeros_like(v) for v in embed_vals]

                ge = jax.lax.cond(rank == 0, embed_case, no_embed, None)
                st = dict(state)
                st["out_b"] = dx
                st["g_embed"] = [a + b for a, b in zip(state["g_embed"], ge)]
                st["loss"] = state["loss"] + lval / M
                return st

            def w_br(state, m):
                x = get_slot(state["instash"], m)
                dy = get_slot(state["dystash"], m)

                def last_case(_):
                    _, vjp = jax.vjp(
                        lambda sp, hv: self._last_chain(sp, hv, x,
                                                        labels_mb[m]),
                        stage_params, head_vals)
                    gs, gh = vjp(jnp.asarray(1.0 / M, jnp.float32))
                    return list(gs), list(gh)

                def mid_case(_):
                    _, vjp = jax.vjp(
                        lambda sp: self._stage_fwd(sp, x), stage_params)
                    (gs,) = vjp(dy)
                    return list(gs), [jnp.zeros_like(v) for v in head_vals]

                gs, gh = jax.lax.cond(rank == S - 1, last_case, mid_case,
                                      None)
                gs, gh = list(gs), list(gh)
                st = dict(state)
                st["g_stage"] = [a + b for a, b in zip(state["g_stage"], gs)]
                st["g_head"] = [a + b for a, b in zip(state["g_head"], gh)]
                return st

            def tick(state, t):
                # 1. deliver arrivals (sent by neighbors at t-1)
                my_op = op_t[t, rank]
                my_mb = mb_t[t, rank]
                afv = afv_t[t, rank]
                abv = abv_t[t, rank]
                afm = afm_t[t, rank]
                abm = abm_t[t, rank]
                inst = state["instash"]
                inst = jnp.where(afv, set_slot(inst, afm, state["fwd_in"]),
                                 inst)
                dyst = state["dystash"]
                dyst = jnp.where(abv, set_slot(dyst, abm, state["bwd_in"]),
                                 dyst)
                state = dict(state, instash=inst, dystash=dyst)
                # 2. dispatch the scheduled op (collective-free branches)
                state = jax.lax.switch(
                    my_op,
                    [idle_br, f_br, b_br, w_br],
                    state, my_mb)
                # 3. unconditional hops (every rank, every tick)
                state = dict(
                    state,
                    fwd_in=jax.lax.ppermute(state["out_f"], "pp", fwd_perm),
                    bwd_in=jax.lax.ppermute(state["out_b"], "pp", bwd_perm))
                return state, None

            state, _ = jax.lax.scan(tick, state, jnp.arange(T))
            loss = jax.lax.psum(state["loss"], "pp")  # only last rank adds
            # stack grads back over pp; embed/head grads live on one rank
            g_stage = tuple(g[None] for g in state["g_stage"])
            g_embed = tuple(jax.lax.psum(g, "pp") for g in state["g_embed"])
            g_head = tuple(jax.lax.psum(g, "pp") for g in state["g_head"])
            return loss, g_stage, g_embed, g_head

        in_specs = (
            tuple(self._block_specs),
            tuple(PartitionSpec() for _ in self._embed_vals),
            tuple(PartitionSpec() for _ in self._head_vals),
            PartitionSpec(),
            PartitionSpec(),
        )
        out_specs = (
            PartitionSpec(),
            tuple(self._block_specs),
            tuple(PartitionSpec() for _ in self._embed_vals),
            tuple(PartitionSpec() for _ in self._head_vals),
        )
        smapped = _shard_map(
            lambda bl, ev, hv, i, l: body(bl, ev, hv, i, l),
            self.mesh, in_specs, out_specs)
        self._jitted = jax.jit(smapped)

    def run(self, ids, labels):
        """ids/labels: [M*mb, seq] numpy/jnp arrays."""
        ids = np.asarray(ids)
        labels = np.asarray(labels)
        mbs = ids.shape[0] // self.M
        ids_mb = jnp.asarray(ids.reshape((self.M, mbs) + ids.shape[1:]))
        labels_mb = jnp.asarray(
            labels.reshape((self.M, mbs) + labels.shape[1:]))
        if self._jitted is None:
            emb_probe = self._embed_fwd(self._embed_vals, ids_mb[0])
            self._build(tuple(emb_probe.shape), ids_mb.dtype)
        loss, g_stage, g_embed, g_head = self._jitted(
            tuple(self._stacked_blocks), tuple(self._embed_vals),
            tuple(self._head_vals), ids_mb, labels_mb)
        return loss, (list(g_embed), list(g_stage), list(g_head))

    def __call__(self, ids, labels):
        """Train step: ZB-H1 forward/backward + optimizer update (the Fleet
        train_batch contract, like PipelinedTrainStep)."""
        ids = ids._value if isinstance(ids, Tensor) else ids
        labels = labels._value if isinstance(labels, Tensor) else labels
        loss, (g_embed, g_stage, g_head) = self.run(np.asarray(ids),
                                                    np.asarray(labels))
        if self.optimizer is None:
            return Tensor(loss)
        flat_p = list(self._embed_vals) + list(self._stacked_blocks) \
            + list(self._head_vals)
        flat_g = list(g_embed) + list(g_stage) + list(g_head)
        if self._update_jit is None:
            from paddle_tpu.parallel.train_step import apply_optimizer_update

            def upd(params, grads, states, lr, step_i):
                return apply_optimizer_update(self.optimizer, params, grads,
                                              states, lr, step_i)

            self._update_jit = jax.jit(upd, donate_argnums=(0, 2))
        self._step_i += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        new_p, self._opt_states = self._update_jit(
            flat_p, flat_g, self._opt_states, lr,
            jnp.asarray(self._step_i, jnp.int32))
        ne = len(self._embed_vals)
        nb = len(self._stacked_blocks)
        self._embed_vals = list(new_p[:ne])
        self._stacked_blocks = list(new_p[ne:ne + nb])
        self._head_vals = list(new_p[ne + nb:])
        # checkpoint parity: state_dict must reflect the trained step count
        # (moments live in this step's _opt_states, like PipelinedTrainStep)
        self.optimizer._step_count = self._step_i
        return Tensor(loss)

    def sync_params_to_model(self):
        for p, v in zip(self._embed_params, self._embed_vals):
            p._set_value(v)
        for p, v in zip(self._head_params, self._head_vals):
            p._set_value(v)
        for i, stacked in enumerate(self._stacked_blocks):
            flat = self._unstack(stacked)
            for l, bp in enumerate(self._block_params):
                bp[i]._set_value(flat[l])

    def _unstack(self, arr):
        return arr.reshape((self.S * self.bps,) + arr.shape[2:])

    def sync_states_to_optimizer(self):
        """Checkpoint parity (see train_step.sync_pipeline_states_to_optimizer)."""
        if self.optimizer is None or self._opt_states is None:
            return
        from paddle_tpu.parallel.train_step import (
            sync_pipeline_states_to_optimizer)

        sync_pipeline_states_to_optimizer(
            self.optimizer, self._opt_states, self._embed_params,
            self._head_params, self._block_params, self._unstack,
            self._step_i)
