"""Executable ZB-H1 zero-bubble pipeline schedule.

Reference parity: pipeline_zero_bubble.py (distributed/passes/
pipeline_scheduler_pass/) executes {F, B, W} job lists per rank, where the
backward is split into B (activation grad — on the inter-stage critical path)
and W (weight grad — no downstream consumer, fills the drain bubble). The
reference realises the split by cutting matmul_grad into its dX and dW
matmuls (pipeline_zero_bubble.py:111) with zero recompute; this module does
the same generically at the jaxpr level.

TPU-native design (round-5 rewrite): the static zb_h1_schedule tick table is
UNROLLED into one XLA program (shard_map over 'pp'):

* Zero recompute. Each F tick runs the stage forward ONCE via `jax.vjp` and
  extracts the vjp residuals with `jax.closure_convert`; B and W ticks replay
  slices of a pre-built backward jaxpr on the stashed residuals.
* True B/W split with a cut. `_split_bwd` partitions the backward jaxpr into
  the dX slice (B: every equation the input cotangent needs) and the dW
  remainder (W); interior cotangents crossing the cut are EXPORTED by B and
  consumed by W, so W recomputes nothing — the generic analog of splitting
  matmul_grad into its dX and dW matmuls. The per-stage block loop is
  unrolled (no lax.scan) so the cut lands between individual matmuls.
* SSA stashes. Because the tick loop is unrolled (T is static), residuals,
  arrived activations/cotangents and cut values are plain traced values
  selected by static `where(rank == r, ...)` chains — no carried ring
  buffers, no dynamic_update_slice copies, no state dict flowing through the
  switch (the round-4 tick machine paid ~13-21 ms/tick for exactly that).
* Static hop elision. ppermute hops are emitted only on ticks that actually
  transfer an activation (forward) or cotangent (backward); drain (all-W)
  ticks carry no hops at all.
* Per-tick switch specialisation. Each tick's `lax.switch` contains only the
  op kinds present in that tick's table row, and its output tuple only the
  components that tick can produce; grad accumulators are threaded through
  the switch only on ticks that can update them.

Labels caveat: `jax.closure_convert` hoists only inexact-dtype closure
values; integer (label-derived) residuals stay baked in the converted
function, so the last-stage backward jaxpr is built PER MICROBATCH with that
microbatch's labels (statically known per tick). The same mechanism imposes
a restriction on BLOCKS: a block backward may not save an
activation-DERIVED integer/bool residual (e.g. a custom_vjp stashing
`x > 0` as bool) — it would bake at the probe's zeros-input value. Standard
blocks save float residuals (hoisted per-tick) and weight/shape-derived
values (input-independent), both safe; the grad-parity test is the gate.

Cost model: one microbatch costs F + B(dX slice) + W(dW remainder) = exactly
one forward + one backward, like 1F1B, while the W ticks fill 1F1B's
(S-1)/(M+S-1) drain bubble.
"""
from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed.mesh import get_mesh
from paddle_tpu.distributed.mesh import shard_map_compat as _shard_map
from paddle_tpu.parallel.pipeline_schedules import zb_h1_schedule
from paddle_tpu.parallel.train_step import functional_call

__all__ = ["ZBH1PipelinedStep"]

_OP = {"F": 1, "B": 2, "W": 3}


def _tables(sched, S, M):
    """Static schedule tables: op/mb codes [T, S] plus, per (rank, mb), the
    tick at which rank r runs F/B/W on that microbatch."""
    ticks = sched["ticks"]
    T = len(ticks)
    op = np.zeros((T, S), np.int32)
    mb = np.zeros((T, S), np.int32)
    f_tick = [[-1] * M for _ in range(S)]
    b_tick = [[-1] * M for _ in range(S)]
    w_tick = [[-1] * M for _ in range(S)]
    by_kind = {"F": f_tick, "B": b_tick, "W": w_tick}
    for t, row in enumerate(ticks):
        for r, cell in enumerate(row):
            if cell is not None:
                kind, m, _ = cell
                op[t, r] = _OP[kind]
                mb[t, r] = m
                by_kind[kind][r][m] = t
    return op, mb, f_tick, b_tick, w_tick


def _eval_eqns(eqns, env, outvars):
    """Evaluate a topologically-ordered equation list against env (the
    core.eval_jaxpr inner loop, over a subset of equations)."""
    from jax._src.core import Literal

    def read(v):
        return v.val if isinstance(v, Literal) else env[v]

    for eqn in eqns:
        subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
        ans = eqn.primitive.bind(*subfuns, *[read(v) for v in eqn.invars],
                                 **bind_params)
        if eqn.primitive.multiple_results:
            for v, a in zip(eqn.outvars, ans):
                env[v] = a
        else:
            env[eqn.outvars[0]] = ans
    return [read(v) for v in outvars]


def _split_bwd(closed, n_w):
    """Split a backward jaxpr (dy, *consts) -> (w_grads..., dx) into the
    B slice (everything dx needs) and the W remainder, with the interior
    values crossing the cut exported by B and consumed by W — the generic,
    zero-recompute form of the reference's matmul_grad split into its dX
    and dW matmuls (pipeline_zero_bubble.py:111).

    Returns (b_fn, w_fn, cut_avals):
      b_fn(*args) -> (dx, cuts)          args = (dy, *consts)
      w_fn(args, cuts) -> w_grads list
    """
    from jax._src.interpreters import partial_eval as pe
    from jax._src.core import Literal

    jaxpr = pe.convert_constvars_jaxpr(closed.jaxpr)
    consts = list(closed.consts)
    eqns = list(jaxpr.eqns)
    outvars = list(jaxpr.outvars)
    dx_var = outvars[-1]
    w_vars = outvars[:n_w]

    def needed_ids(roots):
        need = {v for v in roots if not isinstance(v, Literal)}
        sel = set()
        for eqn in reversed(eqns):
            if any(o in need for o in eqn.outvars):
                sel.add(id(eqn))
                need.update(v for v in eqn.invars
                            if not isinstance(v, Literal))
        return sel

    ids_x = needed_ids([dx_var])
    ids_w = needed_ids(w_vars)
    b_eqns = [e for e in eqns if id(e) in ids_x]
    w_eqns = [e for e in eqns if id(e) in ids_w and id(e) not in ids_x]
    produced_b = {o for e in b_eqns for o in e.outvars}
    cut, seen = [], set()
    for e in w_eqns:
        for iv in e.invars:
            if (not isinstance(iv, Literal) and iv in produced_b
                    and iv not in seen):
                seen.add(iv)
                cut.append(iv)
    # a w output may be produced directly by the B slice (e.g. a bias grad
    # equal to an interior cotangent reduction) — export it over the cut too
    for v in w_vars:
        if v in produced_b and v not in seen and not isinstance(v, Literal):
            seen.add(v)
            cut.append(v)
    cut_avals = [v.aval for v in cut]
    invars = list(jaxpr.invars)

    def b_fn(*args):
        env = dict(zip(invars, consts + list(args)))
        outs = _eval_eqns(b_eqns, env, [dx_var] + cut)
        return outs[0], outs[1:]

    def w_fn(args, cuts):
        env = dict(zip(invars, consts + list(args)))
        env.update(zip(cut, cuts))
        return _eval_eqns(w_eqns, env, w_vars)

    return b_fn, w_fn, cut_avals


class ZBH1PipelinedStep:
    """ZB-H1 for (embed, blocks, head) models on a pp-only mesh.

    run(ids, labels) -> (loss, (embed_grads, stacked_block_grads, head_grads))
    with grads numerically equal to the dense model's (parity-tested).
    ids/labels: [M * mb_size, seq]-style arrays split into M microbatches on
    the leading dim."""

    def __init__(self, embed_layer, blocks: Sequence, head_layer,
                 loss_fn: Callable, mesh: Mesh | None = None,
                 num_micro: int = 2, seed: int = 0, optimizer=None,
                 debug: bool = False, remat: bool | str = False,
                 zero_axis: str | None = None,
                 fp8_policy: str | None = None):
        from paddle_tpu.amp.fp8 import normalize_fp8_policy
        from paddle_tpu.core.flags import flag
        from paddle_tpu.parallel.scan_layers import normalize_remat

        # fp8: stateless current scaling (like PipelinedTrainStep) — the
        # fp8_dot_current custom_vjp slices cleanly through the B/W jaxpr
        # split because its backward needs only the stashed quantized
        # operands, no cross-step state
        self.fp8_policy = normalize_fp8_policy(
            flag("fp8_policy") if fp8_policy is None else fp8_policy)

        # ZB-H1 is ZERO-recompute by construction: every residual the
        # backward needs is stashed at the F tick and replayed by the B/W
        # jaxpr slices, and the B/W cut requires the UNROLLED, uncheckpointed
        # block loop (a jax.checkpoint'd or scanned block is one atomic
        # equation to the slicer, collapsing W into B — i.e. back to 1F1B).
        # The knob exists for API uniformity with PipelinedTrainStep; any
        # recomputing policy is rejected rather than silently ignored.
        self.remat_policy = normalize_remat(remat)
        if self.remat_policy != "none":
            raise ValueError(
                f"ZBH1PipelinedStep is zero-recompute by design; remat "
                f"policy {self.remat_policy!r} is not applicable (use "
                f"PipelinedTrainStep for selective rematerialization)")
        # debug=True additionally returns every tick's sent activation /
        # cotangent (per rank) from run(), in self._dbg_out — the parity
        # debugging view used by tests
        self._debug = bool(debug)
        self.mesh = mesh if mesh is not None else get_mesh()
        if self.mesh is None or "pp" not in self.mesh.shape:
            raise ValueError("ZBH1PipelinedStep requires a mesh with a 'pp' axis")
        self.S = int(self.mesh.shape["pp"])
        if len(blocks) % self.S != 0:
            raise ValueError(f"{len(blocks)} blocks not divisible by pp={self.S}")
        self.bps = len(blocks) // self.S
        self.M = int(num_micro)
        self.embed = embed_layer
        self.blocks = list(blocks)
        self.head = head_layer
        self.loss_fn = loss_fn
        self._key = jax.random.key(seed)

        self.sched = zb_h1_schedule(self.S, self.M)
        (self._op, self._mb, self._f_tick, self._b_tick,
         self._w_tick) = _tables(self.sched, self.S, self.M)
        self.T = len(self.sched["ticks"])
        # residual-liveness window (informational; the unrolled program's
        # buffers are sized by XLA liveness, not by a carried ring buffer).
        # Residuals live from a microbatch's F tick until its W tick (B only
        # adds the cut tensors), so count the peak F->W overlap per rank.
        self.cap = 1
        for r in range(self.S):
            for m in range(self.M):
                live = sum(1 for m2 in range(self.M)
                           if self._f_tick[r][m2] <= self._f_tick[r][m]
                           <= self._w_tick[r][m2])
                self.cap = max(self.cap, live)

        mesh = self.mesh
        self._embed_params = embed_layer.parameters()
        self._head_params = head_layer.parameters()
        self._block_params = [b.parameters() for b in blocks]
        nb = len(self._block_params[0])
        stacked = []
        for i in range(nb):
            vals = [bp[i]._value for bp in self._block_params]
            stacked.append(jnp.stack(vals).reshape(
                (self.S, self.bps) + vals[0].shape))
        # ZeRO-3 persistence composes with pp: each stage's block params ALSO
        # live reduce-scattered over `zero_axis` and are all-gathered ONCE at
        # stage entry (the unrolled-jaxpr B/W split needs the full stage
        # weights as stable loop invariants, so there is no per-block
        # gather-ahead here — persistence is 1/(pp*shard), in-step liveness
        # stays one stage). Weight grads return reduce-scattered
        # (psum_scatter / shard_size: the batch is replicated over the axis).
        self.zero_axis = None
        self._zero_dims = [None] * nb
        if zero_axis is not None and zero_axis not in mesh.shape:
            import warnings

            warnings.warn(
                f"zero_axis={zero_axis!r} is not a mesh axis "
                f"({tuple(mesh.shape)}); per-stage ZeRO sharding is OFF")
        if (zero_axis is not None and zero_axis in mesh.shape
                and mesh.shape[zero_axis] > 1):
            self.zero_axis = zero_axis
        self._block_specs = []
        for i, a in enumerate(stacked):
            dims = ["pp"] + [None] * (a.ndim - 1)
            if self.zero_axis is not None:
                for d in range(2, a.ndim):
                    if a.shape[d] % mesh.shape[self.zero_axis] == 0:
                        dims[d] = self.zero_axis
                        # gather axis after the leading pp dim is stripped
                        self._zero_dims[i] = d - 1
                        break
            self._block_specs.append(PartitionSpec(*dims))
        if all(d is None for d in self._zero_dims):
            if self.zero_axis is not None:
                import warnings

                warnings.warn(
                    f"zero_axis={self.zero_axis!r}: no block param dim "
                    f"divides the axis; per-stage params persist REPLICATED")
            self.zero_axis = None
        self._stacked_blocks = [
            jax.device_put(a, NamedSharding(mesh, s))
            for a, s in zip(stacked, self._block_specs)]
        self._embed_vals = [jax.device_put(p._value, NamedSharding(mesh, PartitionSpec()))
                            for p in self._embed_params]
        self._head_vals = [jax.device_put(p._value, NamedSharding(mesh, PartitionSpec()))
                           for p in self._head_params]
        self._jitted = None

        # optional optimizer: ZB-H1 as a full Fleet train-batch mode
        self.optimizer = optimizer
        self._opt_states = None
        self._update_jit = None
        # async feed/dispatch: bound un-fetched steps in flight, accept
        # pre-placed device batches without a host round-trip
        from paddle_tpu.io.device_feed import DispatchWindow

        self._window = DispatchWindow()
        self.h2d_transfers = 0  # input leaves actually moved host->device
        # resume parity: continue from a restored optimizer's step count
        from paddle_tpu.parallel.train_step import _innermost_opt

        self._step_i = (int(getattr(_innermost_opt(optimizer), "_step_count",
                                    0) or 0) if optimizer is not None else 0)
        if optimizer is not None:
            from paddle_tpu.parallel.train_step import init_opt_states

            # resume path: a restored optimizer._state (elastic checkpoint /
            # set_state_dict) seeds the moments instead of zero re-init
            self._opt_states = init_opt_states(
                optimizer,
                self._embed_vals + self._stacked_blocks + self._head_vals,
                params=(self._embed_params
                        + [None] * len(self._stacked_blocks)
                        + self._head_params),
                block_params=self._block_params, stack=self._stack)

    # -- pure per-rank compute pieces ---------------------------------------

    def _stage_fwd(self, stage_params, x):
        # unrolled block loop (NOT lax.scan): the B/W jaxpr cut must land
        # between individual matmuls, and scans are atomic to the slicer
        for i in range(self.bps):
            lp = [a[i] for a in stage_params]
            out = functional_call(self.blocks[0], lp, (Tensor(x),))
            x = out._value if isinstance(out, Tensor) else out
        return x

    def _embed_fwd(self, embed_vals, ids_mb):
        out = functional_call(self.embed, embed_vals, (Tensor(ids_mb),))
        return out._value if isinstance(out, Tensor) else out

    def _last_chain(self, stage_params, head_vals, x, labels_mb):
        """loss(head(stage(x))) for the last rank."""
        y = self._stage_fwd(stage_params, x)
        from paddle_tpu.parallel.fused_head import (fused_head_loss,
                                                    fused_head_spec)

        fspec = fused_head_spec(self.head, self.loss_fn)
        if fspec is not None:
            # chunked fused head+CE (no [tokens, vocab] logits); labels are
            # closure constants here, satisfying the integer-residual rule
            # this module's docstring describes
            return fused_head_loss(self.head, head_vals, y, labels_mb,
                                   fspec).astype(jnp.float32)
        from paddle_tpu.amp.fp8 import head_scope

        with head_scope():
            h = functional_call(self.head, head_vals, (Tensor(y),))
        hv = h._value if isinstance(h, Tensor) else h
        loss = self.loss_fn(Tensor(hv), Tensor(labels_mb))
        return (loss._value if isinstance(loss, Tensor) else loss).astype(jnp.float32)

    # -- the compiled schedule ----------------------------------------------

    def _build(self, mb_shape, ids_dtype):
        mesh, S, M, T = self.mesh, self.S, self.M, self.T
        op, mb = self._op, self._mb
        f_tick, b_tick = self._f_tick, self._b_tick
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]
        f32 = jnp.float32

        from contextlib import nullcontext

        from paddle_tpu.parallel.segments import segment_execution

        def body(stacked_local, embed_vals, head_vals, ids_mb, labels_mb,
                 extras):
            rank = jax.lax.axis_index("pp")
            stage_params = [a[0] for a in stacked_local]
            zshard = (self.mesh.shape[self.zero_axis]
                      if self.zero_axis is not None else 1)
            if self.zero_axis is not None:
                # reconstitute this stage's full weights ONCE (stable loop
                # invariants for every F/B/W jaxpr below)
                stage_params = [
                    p if d is None
                    else jax.lax.all_gather(p, self.zero_axis, axis=d,
                                            tiled=True)
                    for p, d in zip(stage_params, self._zero_dims)]
            n_sp = len(stage_params)
            n_hv = len(head_vals)
            zero_act = jnp.zeros(mb_shape, f32)
            inv_m = jnp.asarray(1.0 / M, f32)
            # packed-batch metadata ([M, mb, S] per leaf), delivered to the
            # blocks through the segment context. Microbatch indices are
            # STATIC schedule-table entries, so per-mb slices are static
            # selects at F/embed/last-chain construction; the mid-stage
            # residual stash carries the captured values to B/W replay.
            seg_mb = extras.get("segment_ids") if extras else None
            pos_mb = extras.get("position_ids") if extras else None
            has_ex = seg_mb is not None or pos_mb is not None

            def ex_ctx(seg, pos):
                return (segment_execution(seg, pos) if has_ex
                        else nullcontext())

            def ex_of(m):
                return (seg_mb[m] if seg_mb is not None else None,
                        pos_mb[m] if pos_mb is not None else None)

            # ---- one-time backward construction (probe traces; ops that
            # feed only the probe residuals are DCE'd by XLA) -------------
            def mid_fn(sp, xx):
                return self._stage_fwd(sp, xx)

            # vjp residuals include the parameter tensors themselves; those
            # are loop-invariant, so they are filtered OUT of the stash path
            # (by tracer identity, deterministic across traces) and
            # re-supplied from scope at B/W time — params never flow through
            # switch outputs, zero padding, or where-chains.
            param_ids = {id(p) for p in stage_params} | {
                id(v) for v in head_vals}

            def split_consts(consts):
                dyn = [c for c in consts if id(c) not in param_ids]
                pmap = {i: c for i, c in enumerate(consts)
                        if id(c) in param_ids}
                return dyn, pmap

            def merge_consts(dyn, pmap, total):
                out, di = [], 0
                for i in range(total):
                    if i in pmap:
                        out.append(pmap[i])
                    else:
                        out.append(dyn[di])
                        di += 1
                return out

            # the probe trace runs under microbatch-0's context so the
            # captured-residual STRUCTURE (shapes incl. the int32 ids)
            # matches every per-tick trace
            with ex_ctx(*ex_of(0)):
                _, vjp_m = jax.vjp(mid_fn, stage_params, zero_act)
            pure_m, cm_ex = jax.closure_convert(vjp_m, zero_act)
            cm_dyn_ex, cm_pmap = split_consts(cm_ex)
            cm_total = len(cm_ex)
            cm_shapes = [(c.shape, c.dtype) for c in cm_dyn_ex]
            closed_m = jax.make_jaxpr(
                lambda dy, *c: pure_m(dy, *c))(zero_act, *cm_ex)
            bwd_m_b, bwd_m_w, cutm_avals = _split_bwd(closed_m, n_sp)

            def last_closed(m):
                def fn(sp, hv, xx):
                    with ex_ctx(*ex_of(m)):
                        return self._last_chain(sp, hv, xx, labels_mb[m])

                return fn

            zero_scalar = jnp.zeros((), f32)
            # built PER MICROBATCH at BODY level: closure_convert bakes
            # integer (label-derived) residuals into the converted function,
            # and branch-scoped construction would leak branch tracers
            bwd_l = {}
            cl_shapes = cutl_avals = cl_pmap = cl_total = None
            for m in range(M):
                _, vjp_l = jax.vjp(last_closed(m), stage_params, head_vals,
                                   zero_act)
                pure_l, cl_ex = jax.closure_convert(vjp_l, zero_scalar)
                dyn_m, pmap_m = split_consts(cl_ex)
                shapes_m = [(c.shape, c.dtype) for c in dyn_m]
                closed_l = jax.make_jaxpr(
                    lambda dy, *c: pure_l(dy, *c))(zero_scalar, *cl_ex)
                b_fn, w_fn, cuts_m = _split_bwd(closed_l, n_sp + n_hv)
                bwd_l[m] = (b_fn, w_fn)
                if cl_shapes is None:
                    cl_shapes, cutl_avals = shapes_m, cuts_m
                    cl_pmap, cl_total = pmap_m, len(cl_ex)
                else:
                    assert (shapes_m == cl_shapes
                            and set(pmap_m) == set(cl_pmap)
                            and [(a.shape, a.dtype) for a in cuts_m] == [
                                (a.shape, a.dtype) for a in cutl_avals]), \
                        "per-microbatch last-chain backward structure diverges"

            def fwd_mid(x, ex=None):
                """Forward once; residuals extracted, zero recompute later.
                `ex`: this tick's (segment_ids, position_ids) selection —
                captured into the stashed residuals, so B/W replay the
                right microbatch's masks without retracing the blocks."""
                with (ex_ctx(*ex) if ex is not None else nullcontext()):
                    y, vjp = jax.vjp(mid_fn, stage_params, x)
                _, consts = jax.closure_convert(vjp, zero_act)
                dyn, pmap = split_consts(consts)
                assert ([(c.shape, c.dtype) for c in dyn] == cm_shapes
                        and set(pmap) == set(cm_pmap)), \
                    "non-deterministic vjp residual structure (mid stage)"
                return y, dyn

            def fwd_last(x, m):
                lossv, vjp = jax.vjp(last_closed(m), stage_params, head_vals,
                                     x)
                _, consts = jax.closure_convert(vjp, zero_scalar)
                dyn, pmap = split_consts(consts)
                assert ([(c.shape, c.dtype) for c in dyn] == cl_shapes
                        and set(pmap) == set(cl_pmap)), \
                    "non-deterministic vjp residual structure (last stage)"
                return lossv, dyn

            zeros_cm = [jnp.zeros(s, d) for s, d in cm_shapes]
            zeros_cl = [jnp.zeros(s, d) for s, d in cl_shapes]
            zeros_cutm = [jnp.zeros(a.shape, a.dtype) for a in cutm_avals]
            zeros_cutl = [jnp.zeros(a.shape, a.dtype) for a in cutl_avals]

            # ---- unrolled tick program -----------------------------------
            fwd_recv = {}      # tick -> arrived activation (per-rank valid)
            bwd_recv = {}      # tick -> arrived cotangent
            cm_out = {}        # tick -> mid residuals produced at that F tick
            cl_out = {}        # tick -> last-rank residuals
            cutm_out = {}      # tick -> interior values exported by a mid B
            cutl_out = {}      # tick -> ... by a last-rank B
            g_sp = [jnp.zeros_like(p) for p in stage_params]
            g_hv = [jnp.zeros_like(v) for v in head_vals]
            g_e = [jnp.zeros_like(v) for v in embed_vals]
            loss = jnp.zeros((), f32)
            dbg = {}

            for t in range(T):
                F_rs = [r for r in range(S) if op[t, r] == 1]
                B_rs = [r for r in range(S) if op[t, r] == 2]
                W_rs = [r for r in range(S) if op[t, r] == 3]
                if not (F_rs or B_rs or W_rs):
                    continue

                # -- static input preselection (cheap where-chains) --------
                def chain(rs, of):
                    val = of(rs[0])
                    if isinstance(val, list):
                        for r in rs[1:]:
                            src = of(r)
                            val = [jnp.where(rank == r, s, d)
                                   for s, d in zip(src, val)]
                        return val
                    for r in rs[1:]:
                        val = jnp.where(rank == r, of(r), val)
                    return val

                x_f = None
                if F_rs:
                    def x_of(r):
                        m = mb[t, r]
                        if r == 0:
                            with ex_ctx(*ex_of(m)):
                                return self._embed_fwd(embed_vals, ids_mb[m])
                        return fwd_recv[f_tick[r - 1][m]]

                    x_f = chain(F_rs, x_of)

                bw_rs = B_rs + W_rs
                mid_bw = [r for r in bw_rs if r < S - 1]
                last_bw = (S - 1) in bw_rs
                dy_sel = (chain(mid_bw, lambda r: bwd_recv[
                    b_tick[r + 1][mb[t, r]]]) if mid_bw else None)
                cm_sel = (chain(mid_bw, lambda r: cm_out[
                    f_tick[r][mb[t, r]]]) if mid_bw else None)
                cl_sel = (cl_out[f_tick[S - 1][mb[t, S - 1]]]
                          if last_bw else None)
                mid_w = [r for r in W_rs if r < S - 1]
                last_w = (S - 1) in W_rs
                cutm_sel = (chain(mid_w, lambda r: cutm_out[
                    b_tick[r][mb[t, r]]]) if mid_w else None)
                cutl_sel = (cutl_out[b_tick[S - 1][mb[t, S - 1]]]
                            if last_w else None)

                # -- which outputs can this tick produce (static)? ---------
                mids_f = [r for r in F_rs if r < S - 1]
                last_f = (S - 1) in F_rs
                mid_b = [r for r in B_rs if r < S - 1]
                last_b = (S - 1) in B_rs
                send_fwd = bool(mids_f)
                send_bwd = any(r > 0 for r in B_rs)
                prod_cm = bool(mids_f)
                prod_cl = last_f
                prod_loss = last_f
                prod_cutm = bool(mid_b)
                prod_cutl = last_b
                upd_gsp = bool(W_rs)
                upd_ghv = last_w
                upd_ge = 0 in B_rs

                def ret(y=None, dx=None, cm=None, cl=None, cutm=None,
                        cutl=None, lossv=None, gsp=None, ghv=None, ge=None):
                    out = []
                    if send_fwd:
                        out.append(y if y is not None else zero_act)
                    if send_bwd:
                        out.append(dx if dx is not None else zero_act)
                    if prod_cm:
                        out.extend(cm if cm is not None else zeros_cm)
                    if prod_cl:
                        out.extend(cl if cl is not None else zeros_cl)
                    if prod_cutm:
                        out.extend(cutm if cutm is not None else zeros_cutm)
                    if prod_cutl:
                        out.extend(cutl if cutl is not None else zeros_cutl)
                    if prod_loss:
                        out.append(lossv if lossv is not None
                                   else jnp.zeros((), f32))
                    if upd_gsp:
                        out.extend(gsp if gsp is not None else acc_gsp)
                    if upd_ghv:
                        out.extend(ghv if ghv is not None else acc_ghv)
                    if upd_ge:
                        out.extend(ge if ge is not None else acc_ge)
                    return tuple(out)

                # this tick's extras for the MID ranks running F: the same
                # where-chain the activation selection uses, so the context
                # value at each rank belongs to the microbatch it processes
                ex_sel = None
                if has_ex and mids_f:
                    ex_sel = tuple(
                        (chain(mids_f, lambda r, tab=tab: tab[mb[t, r]])
                         if tab is not None else None)
                        for tab in (seg_mb, pos_mb))

                def f_branch(t=t, x_f=x_f, mids_f=mids_f, last_f=last_f,
                             ex_sel=ex_sel):
                    m_last = mb[t, S - 1]
                    if mids_f and last_f:
                        def arm_last(xx):
                            lossv, cl = fwd_last(xx, m_last)
                            return (zero_act, zeros_cm, cl, lossv)

                        def arm_mid(xx):
                            y, cm = fwd_mid(xx, ex_sel)
                            return (y, cm, zeros_cl, jnp.zeros((), f32))

                        y, cm, cl, lossv = jax.lax.cond(
                            rank == S - 1, arm_last, arm_mid, x_f)
                        return ret(y=y, cm=cm, cl=cl, lossv=lossv)
                    if last_f:
                        lossv, cl = fwd_last(x_f, m_last)
                        return ret(cl=cl, lossv=lossv)
                    y, cm = fwd_mid(x_f, ex_sel)
                    return ret(y=y, cm=cm)

                def b_branch(t=t, dy_sel=dy_sel, cm_sel=cm_sel, cl_sel=cl_sel,
                             mid_b=mid_b, last_b=last_b):
                    cm_full = (merge_consts(cm_sel, cm_pmap, cm_total)
                               if cm_sel is not None else None)
                    cl_full = (merge_consts(cl_sel, cl_pmap, cl_total)
                               if cl_sel is not None else None)
                    if mid_b and last_b:
                        def arm_last():
                            dx, cuts = bwd_l[mb[t, S - 1]][0](inv_m, *cl_full)
                            return dx, zeros_cutm, cuts

                        def arm_mid():
                            dx, cuts = bwd_m_b(dy_sel, *cm_full)
                            return dx, cuts, zeros_cutl

                        dx, cutm, cutl = jax.lax.cond(
                            rank == S - 1, arm_last, arm_mid)
                    elif last_b:
                        dx, cutl = bwd_l[mb[t, S - 1]][0](inv_m, *cl_full)
                        cutm = None
                    else:
                        dx, cutm = bwd_m_b(dy_sel, *cm_full)
                        cutl = None
                    ge = None
                    if upd_ge:
                        m0 = mb[t, 0]

                        def egrad(dxv):
                            with ex_ctx(*ex_of(m0)):
                                _, evjp = jax.vjp(
                                    lambda ev: self._embed_fwd(ev, ids_mb[m0]),
                                    embed_vals)
                            (g,) = evjp(dxv)
                            return [a + b for a, b in zip(acc_ge, g)]

                        ge = jax.lax.cond(
                            rank == 0, egrad, lambda _: list(acc_ge), dx)
                    return ret(dx=dx, cutm=cutm, cutl=cutl, ge=ge)

                def w_branch(t=t, dy_sel=dy_sel, cm_sel=cm_sel, cl_sel=cl_sel,
                             cutm_sel=cutm_sel, cutl_sel=cutl_sel,
                             mid_w=mid_w, last_w=last_w):
                    cm_full = (merge_consts(cm_sel, cm_pmap, cm_total)
                               if cm_sel is not None else None)
                    cl_full = (merge_consts(cl_sel, cl_pmap, cl_total)
                               if cl_sel is not None else None)

                    def arm_mid():
                        gs = bwd_m_w((dy_sel, *cm_full), cutm_sel)
                        gsp = [a + b for a, b in zip(acc_gsp, gs)]
                        return (gsp, list(acc_ghv)) if upd_ghv else (gsp,)

                    def arm_last():
                        outs = bwd_l[mb[t, S - 1]][1](
                            (inv_m, *cl_full), cutl_sel)
                        gsp = [a + b for a, b in zip(acc_gsp, outs[:n_sp])]
                        ghv = [a + b for a, b in
                               zip(acc_ghv, outs[n_sp:n_sp + n_hv])]
                        return (gsp, ghv)

                    if mid_w and last_w:
                        res = jax.lax.cond(rank == S - 1, arm_last, arm_mid)
                    elif last_w:
                        res = arm_last()
                    else:
                        res = arm_mid()
                    return ret(gsp=res[0], ghv=res[1] if upd_ghv else None)

                def idle_branch():
                    return ret()

                # -- assemble + dispatch the per-tick switch ---------------
                acc_gsp = g_sp if upd_gsp else []
                acc_ghv = g_hv if upd_ghv else []
                acc_ge = g_e if upd_ge else []

                kinds = []
                if len(F_rs) + len(B_rs) + len(W_rs) < S:
                    kinds.append((0, idle_branch))
                if F_rs:
                    kinds.append((1, f_branch))
                if B_rs:
                    kinds.append((2, b_branch))
                if W_rs:
                    kinds.append((3, w_branch))
                lut = np.zeros(4, np.int32)
                for pos, (code, _) in enumerate(kinds):
                    lut[code] = pos
                if len(kinds) == 1:
                    out = kinds[0][1]()
                else:
                    my_op = jnp.asarray(op[t])[rank]
                    idx = jnp.asarray(lut)[my_op]
                    out = jax.lax.switch(idx, [br for _, br in kinds])

                # -- unpack + post-tick bookkeeping ------------------------
                i = 0
                if send_fwd:
                    fwd_recv[t] = jax.lax.ppermute(out[i], "pp", fwd_perm)
                    if getattr(self, "_debug", False):
                        dbg[f"y_t{t}"] = out[i]
                    i += 1
                if send_bwd:
                    bwd_recv[t] = jax.lax.ppermute(out[i], "pp", bwd_perm)
                    if getattr(self, "_debug", False):
                        dbg[f"dx_t{t}"] = out[i]
                    i += 1
                if prod_cm:
                    cm_out[t] = list(out[i:i + len(cm_shapes)])
                    i += len(cm_shapes)
                if prod_cl:
                    cl_out[t] = list(out[i:i + len(cl_shapes)])
                    i += len(cl_shapes)
                if prod_cutm:
                    cutm_out[t] = list(out[i:i + len(cutm_avals)])
                    i += len(cutm_avals)
                if prod_cutl:
                    cutl_out[t] = list(out[i:i + len(cutl_avals)])
                    i += len(cutl_avals)
                if prod_loss:
                    loss = loss + out[i] / M
                    i += 1
                if upd_gsp:
                    g_sp = list(out[i:i + n_sp])
                    i += n_sp
                if upd_ghv:
                    g_hv = list(out[i:i + n_hv])
                    i += n_hv
                if upd_ge:
                    g_e = list(out[i:i + len(g_e)])
                    i += len(g_e)

            loss = jax.lax.psum(loss, "pp")  # only last rank contributed
            if self.zero_axis is not None:
                # back to the reduce-scattered layout: every zero_axis rank
                # computed the SAME full dW (the batch is replicated over the
                # axis), so psum_scatter / shard_size is an exact shard of it
                g_sp = [g if d is None
                        else jax.lax.psum_scatter(
                            g, self.zero_axis, scatter_dimension=d,
                            tiled=True) / zshard
                        for g, d in zip(g_sp, self._zero_dims)]
            g_stage = tuple(g[None] for g in g_sp)
            g_embed = tuple(jax.lax.psum(g, "pp") for g in g_e)
            g_head = tuple(jax.lax.psum(g, "pp") for g in g_hv)
            if getattr(self, "_debug", False):
                return loss, g_stage, g_embed, g_head, {
                    k: v[None] for k, v in dbg.items()}
            return loss, g_stage, g_embed, g_head

        in_specs = (
            tuple(self._block_specs),
            tuple(PartitionSpec() for _ in self._embed_vals),
            tuple(PartitionSpec() for _ in self._head_vals),
            PartitionSpec(),
            PartitionSpec(),
            PartitionSpec(),  # packed-batch extras dict (replicated leaves)
        )
        out_specs = (
            PartitionSpec(),
            tuple(self._block_specs),
            tuple(PartitionSpec() for _ in self._embed_vals),
            tuple(PartitionSpec() for _ in self._head_vals),
        )
        if getattr(self, "_debug", False):
            # single prefix spec covers every debug leaf (leading dim -> pp)
            out_specs = out_specs + (PartitionSpec("pp"),)
        smapped = _shard_map(
            lambda bl, ev, hv, i, l, ex: body(bl, ev, hv, i, l, ex),
            self.mesh, in_specs, out_specs)
        self._jitted = jax.jit(smapped)

    def run(self, ids, labels, *, segment_ids=None, position_ids=None):
        """ids/labels (+ optional KEYWORD-ONLY packed-batch
        segment_ids/position_ids):
        [M*mb, seq] numpy/jnp arrays. Inputs are placed replicated over the
        mesh (ZB-H1 replicates the batch); an input already committed to
        that sharding — a DeviceFeeder batch — skips the device_put, and
        device-resident inputs never round-trip through numpy (the
        microbatch reshape stays on device). The extra leaves reach the
        blocks through the segment context (see `_build`'s body): stashed
        with the F-tick residuals, so B/W replay needs no recompute."""
        iv = ids._value if isinstance(ids, Tensor) else jnp.asarray(ids)
        lv = labels._value if isinstance(labels, Tensor) else jnp.asarray(labels)
        extras = {k: (v._value if isinstance(v, Tensor) else jnp.asarray(v))
                  for k, v in (("segment_ids", segment_ids),
                               ("position_ids", position_ids))
                  if v is not None}
        repl = getattr(self, "_batch_sharding", None)
        if repl is None:
            repl = NamedSharding(self.mesh, PartitionSpec())
            self._batch_sharding = repl

        def place(v):
            if (isinstance(v, jax.Array) and getattr(v, "committed", False)
                    and v.sharding == repl):
                return v  # pre-placed (DeviceFeeder) fast path
            self.h2d_transfers += 1
            return jax.device_put(v, repl)

        iv, lv = place(iv), place(lv)
        mbs = iv.shape[0] // self.M
        ids_mb = iv.reshape((self.M, mbs) + iv.shape[1:])
        labels_mb = lv.reshape((self.M, mbs) + lv.shape[1:])
        extras_mb = {k: place(v).reshape((self.M, mbs) + v.shape[1:])
                     for k, v in extras.items()}
        from paddle_tpu.amp.fp8 import fp8_execution

        # the fp8 session must be live whenever the schedule TRACES (the
        # jaxpr construction in _build and the jitted fn's first call); it
        # is a trace-time thread-local, so steady-state dispatch pays only
        # the context enter/exit
        with fp8_execution(self.fp8_policy):
            if self._jitted is None:
                emb_probe = self._embed_fwd(self._embed_vals, ids_mb[0])
                self._build(tuple(emb_probe.shape), ids_mb.dtype)
            res = self._jitted(
                tuple(self._stacked_blocks), tuple(self._embed_vals),
                tuple(self._head_vals), ids_mb, labels_mb, extras_mb)
        loss, g_stage, g_embed, g_head = res[:4]
        if getattr(self, "_debug", False):
            self._dbg_out = res[4]
        return loss, (list(g_embed), list(g_stage), list(g_head))

    def __call__(self, ids, labels, *, segment_ids=None, position_ids=None):
        """Train step: ZB-H1 forward/backward + optimizer update (the Fleet
        train_batch contract, like PipelinedTrainStep)."""
        loss, (g_embed, g_stage, g_head) = self.run(
            ids, labels, segment_ids=segment_ids, position_ids=position_ids)
        if self.optimizer is None:
            self._window.admit(loss)
            return Tensor(loss)
        flat_p = list(self._embed_vals) + list(self._stacked_blocks) \
            + list(self._head_vals)
        flat_g = list(g_embed) + list(g_stage) + list(g_head)
        if self._update_jit is None:
            from paddle_tpu.parallel.train_step import apply_optimizer_update

            def upd(params, grads, states, lr, step_i):
                return apply_optimizer_update(self.optimizer, params, grads,
                                              states, lr, step_i)

            self._update_jit = jax.jit(upd, donate_argnums=(0, 2))
        self._step_i += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        new_p, self._opt_states = self._update_jit(
            flat_p, flat_g, self._opt_states, lr,
            jnp.asarray(self._step_i, jnp.int32))
        ne = len(self._embed_vals)
        nb = len(self._stacked_blocks)
        self._embed_vals = list(new_p[:ne])
        self._stacked_blocks = list(new_p[ne:ne + nb])
        self._head_vals = list(new_p[ne + nb:])
        # checkpoint parity: state_dict must reflect the trained step count
        # (moments live in this step's _opt_states, like PipelinedTrainStep).
        # Write the INNERMOST optimizer: fleet wraps it and a write on the
        # wrapper would shadow the value its state_dict() actually reads.
        from paddle_tpu.parallel.train_step import _innermost_opt

        _innermost_opt(self.optimizer)._step_count = self._step_i
        self._window.admit(loss)  # bound async run-ahead
        return Tensor(loss)

    def step_async(self, ids, labels, *, segment_ids=None, position_ids=None):
        """Dispatch one step, return a deferred-read LossFuture."""
        from paddle_tpu.io.device_feed import LossFuture

        return LossFuture(self(ids, labels, segment_ids=segment_ids,
                               position_ids=position_ids))

    def drain(self):
        self._window.drain()

    def sync_params_to_model(self):
        for p, v in zip(self._embed_params, self._embed_vals):
            p._set_value(v)
        for p, v in zip(self._head_params, self._head_vals):
            p._set_value(v)
        for i, stacked in enumerate(self._stacked_blocks):
            flat = self._unstack(stacked)
            for l, bp in enumerate(self._block_params):
                bp[i]._set_value(flat[l])

    def _unstack(self, arr):
        return arr.reshape((self.S * self.bps,) + arr.shape[2:])

    def _stack(self, vals):
        """[n_layers] per-layer arrays -> [S, bps, ...] (inverse of
        `_unstack`; resumed optimizer moments go through here)."""
        arr = jnp.stack(list(vals))
        return arr.reshape((self.S, self.bps) + arr.shape[1:])

    def sync_states_to_optimizer(self):
        """Checkpoint parity (see train_step.sync_pipeline_states_to_optimizer)."""
        if self.optimizer is None or self._opt_states is None:
            return
        from paddle_tpu.parallel.train_step import (
            sync_pipeline_states_to_optimizer)

        sync_pipeline_states_to_optimizer(
            self.optimizer, self._opt_states, self._embed_params,
            self._head_params, self._block_params, self._unstack,
            self._step_i)
