"""Profiler (reference: python/paddle/profiler/profiler.py:346 + C++ profiler
paddle/fluid/platform/profiler/profiler.h:47).

TPU-native: host-side RecordEvent spans (the HostTracer analog) + optional
jax.profiler device traces (XLA/xplane, viewable in TensorBoard/xprof — the
CudaTracer/CUPTI analog). Chrome-trace export for the host timeline.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Callable

__all__ = [
    "Profiler", "ProfilerTarget", "RecordEvent", "make_scheduler",
    "export_chrome_tracing", "SummaryView",
]

try:  # the tracing mirror (dependency-free host code; see RecordEvent)
    from paddle_tpu.observability import tracing as _tracing
except ImportError:  # pragma: no cover - partial installs
    _tracing = None


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6


class _Collector:
    """Process-wide event sink (NOT thread-local): background workers —
    DeviceFeeder placement, DataLoader prefetchers — must land in the same
    trace as the main loop; events carry tid, so the chrome timeline still
    separates threads."""

    def __init__(self):
        self.events = []
        self.active = False
        self.lock = threading.Lock()


_collector = _Collector()
_PID = os.getpid()


class RecordEvent:
    """Host event annotation (reference: platform/profiler/event_tracing.h).

    Doubles as the span primitive of the unified observability plane:
    when `paddle_tpu.observability.tracing` has an active collection
    window, every RecordEvent mirrors in there too — carrying the
    thread's current trace id (`tracing.trace_context`), so existing
    annotations (CompiledTrainStep::place/dispatch, DeviceFeeder spans)
    correlate with router/engine request spans in ONE exported file
    without any call-site change."""

    def __init__(self, name: str, event_type=None, attrs: dict | None = None):
        self.name = name
        self.attrs = attrs
        self._begin = None

    def begin(self):
        self._begin = time.perf_counter_ns()

    def end(self):
        if self._begin is None:
            return
        now = time.perf_counter_ns()
        if _collector.active:
            # os.getpid() is a syscall per call (tens of µs in sandboxed
            # kernels) — the cached module value is identical
            ev = {"name": self.name, "ts": self._begin / 1000.0,
                  "dur": (now - self._begin) / 1000.0,
                  "ph": "X", "pid": _PID,
                  "tid": threading.get_ident()}
            if self.attrs:
                ev["args"] = dict(self.attrs)
            with _collector.lock:
                _collector.events.append(ev)
        if _tracing is not None and _tracing.tracing_active():
            _tracing.record_span(self.name, self._begin, now - self._begin,
                                 self.attrs)
        self._begin = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *a):
        self.end()
        return False


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0, skip_first: int = 0):
    total = closed + ready + record

    def sched(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return sched


def export_chrome_tracing(dir_name: str, worker_name: str | None = None) -> Callable:
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_{int(time.time())}.json")
        with open(path, "w") as f:
            json.dump({"traceEvents": prof._events}, f)
        prof._export_path = path

    return handler


class Profiler:
    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self._step = 0
        self._events = []
        self._export_path = None
        self._jax_trace_dir = None

    def start(self):
        with _collector.lock:
            _collector.events = []
        _collector.active = True

    def stop(self):
        _collector.active = False
        with _collector.lock:
            self._events = list(_collector.events)
        if self.on_trace_ready:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        self._step += 1

    def step_info(self, unit=None):
        return f"step {self._step}"

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms", views=None):
        by_name: dict[str, float] = {}
        for e in self._events:
            by_name[e["name"]] = by_name.get(e["name"], 0.0) + e["dur"]
        lines = ["name\ttotal_us"] + [f"{k}\t{v:.1f}" for k, v in sorted(by_name.items(), key=lambda kv: -kv[1])]
        return "\n".join(lines)

    def export(self, path: str, format: str = "json"):
        with open(path, "w") as f:
            json.dump({"traceEvents": self._events}, f)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()
        return False


@contextlib.contextmanager
def device_trace(log_dir: str):
    """XLA device tracing via jax.profiler (xplane; the CUPTI-tracer analog)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
