"""Quantization (reference: python/paddle/quantization — QAT/PTQ, config,
observers/quanters).

TPU-native: int8 inference quantization via fake-quant ops that XLA folds;
QAT inserts straight-through-estimator fake-quant on weights/activations.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Tensor, apply_op
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["QuantConfig", "QAT", "PTQ", "AbsmaxObserver", "FakeQuantLayer",
           "quanted_linear"]


@jax.custom_vjp
def _fake_quant(x, scale):
    q = jnp.clip(jnp.round(x / scale), -127, 127)
    return q * scale


def _fq_fwd(x, scale):
    return _fake_quant(x, scale), None


def _fq_bwd(_, g):  # straight-through estimator
    return g, None


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


class AbsmaxObserver:
    """reference: quantization/observers/abs_max.py."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self.absmax = 0.0

    def observe(self, x: Tensor):
        self.absmax = max(self.absmax, float(jnp.abs(x._value).max()))

    def scale(self) -> float:
        return self.absmax / (2 ** (self.quant_bits - 1) - 1) or 1e-8


class QuantConfig:
    """reference: quantization/config.py."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation or AbsmaxObserver
        self.weight = weight or AbsmaxObserver
        self._types = (nn.Linear, nn.Conv2D)

    def add_layer_config(self, layers, activation=None, weight=None):
        pass

    def quantable(self, layer):
        return isinstance(layer, self._types)


class FakeQuantLayer(Layer):
    def __init__(self, inner, config: QuantConfig):
        super().__init__()
        self.inner = inner
        self.w_observer = config.weight()
        self.a_observer = config.activation()
        self.w_observer.observe(inner.weight)

    def forward(self, x):
        self.a_observer.observe(x)
        xq = apply_op(lambda v: _fake_quant(v, self.a_observer.scale()), x, name="fake_quant")
        w = self.inner.weight
        wq = apply_op(lambda v: _fake_quant(v, self.w_observer.scale()), w, name="fake_quant")
        old = self.inner.weight._value
        self.inner.weight._set_value(wq._value)
        try:
            out = self.inner(xq)
        finally:
            self.inner.weight._set_value(old)
        return out


def _swap(model, config):
    for name, sub in list(model._sub_layers.items()):
        if config.quantable(sub):
            model._sub_layers[name] = FakeQuantLayer(sub, config)
        else:
            _swap(sub, config)
    return model


class QAT:
    """reference: quantization/qat.py."""

    def __init__(self, config: QuantConfig | None = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=False):
        return _swap(model, self.config)

    def convert(self, model, inplace=False):
        return model


class PTQ:
    """reference: quantization/ptq.py — observe calibration batches, then fold
    scales."""

    def __init__(self, config: QuantConfig | None = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=False):
        return _swap(model, self.config)

    def convert(self, model, inplace=False):
        return model


def quanted_linear(x, weight, w_scale, bias=None):
    """int8 weight x bf16 activation matmul (deploy path)."""

    def f(v, w, *b):
        out = jnp.matmul(v, w.astype(v.dtype)) * w_scale
        if b:
            out = out + b[0]
        return out

    args = [x, weight] + ([bias] if bias is not None else [])
    return apply_op(f, *args, name="quanted_linear")
