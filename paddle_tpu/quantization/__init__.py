"""Quantization (reference: python/paddle/quantization — QAT/PTQ, config,
observers/quanters).

TPU-native: int8 inference quantization via fake-quant ops that XLA folds;
QAT inserts straight-through-estimator fake-quant on weights/activations.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Tensor, apply_op
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["QuantConfig", "QAT", "PTQ", "AbsmaxObserver",
           "MovingAverageAbsmaxObserver", "HistObserver",
           "AbsmaxChannelWiseObserver", "FakeQuantLayer", "QuantedLinear",
           "quanted_linear", "quantize_weight_int8", "absmax_scale"]


def absmax_scale(absmax, quant_bits: int = 8, qmax: float | None = None):
    """THE absmax -> scale rule every quantizer in the repo shares (the
    observers' `scale()`/`device_scale()` AND the serving KV page pools):
    ``max(absmax / qmax, 1e-8)``, where qmax defaults to the signed-int
    code range ``2^(bits-1) - 1`` and can be overridden for float formats
    (448 for fp8 e4m3). Device arrays in, device arrays out — callers on
    the decode hot path never pay a host sync."""
    if qmax is None:
        qmax = 2 ** (quant_bits - 1) - 1
    return jnp.maximum(jnp.asarray(absmax, jnp.float32) / qmax, 1e-8)


@jax.custom_vjp
def _fake_quant(x, scale):
    q = jnp.clip(jnp.round(x / scale), -127, 127)
    return q * scale


def _fq_fwd(x, scale):
    q = jnp.round(x / scale)
    # STE with clipping: values whose quantized code saturates contribute no
    # gradient (reference fake_quantize_* ops mask |q| > 127; a plain
    # pass-through would keep pushing weights further past the clip range)
    return jnp.clip(q, -127, 127) * scale, jnp.abs(q) <= 127


def _fq_bwd(mask, g):
    return jnp.where(mask, g, jnp.zeros((), g.dtype)), None


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


class AbsmaxObserver:
    """reference: quantization/observers/abs_max.py.

    The running absmax stays a DEVICE array: `observe()` per step is one
    fused max dispatch with no host sync; only `scale()` materializes a
    Python float (calibration reads it once per quantize call)."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._absmax = None

    @property
    def absmax(self) -> float:
        return 0.0 if self._absmax is None else float(self._absmax)

    def observe(self, x: Tensor):
        cur = jnp.max(jnp.abs(x._value)).astype(jnp.float32)
        self._absmax = cur if self._absmax is None else jnp.maximum(
            self._absmax, cur)

    def scale(self) -> float:
        return self.absmax / (2 ** (self.quant_bits - 1) - 1) or 1e-8

    def device_scale(self):
        """The scale as a device scalar — the QAT fake-quant path consumes
        this, so training steps never block on a device->host read."""
        if self._absmax is None:
            return jnp.float32(1e-8)
        return absmax_scale(self._absmax, self.quant_bits)


class MovingAverageAbsmaxObserver:
    """EMA absmax (reference: observers/ema.py /
    fake_quantize_moving_average_abs_max). Like AbsmaxObserver, the EMA is
    carried as a device array — no per-observe host sync."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        self.quant_bits = quant_bits
        self.rate = moving_rate
        self._absmax = None

    @property
    def absmax(self):
        return None if self._absmax is None else float(self._absmax)

    def observe(self, x: Tensor):
        cur = jnp.max(jnp.abs(x._value)).astype(jnp.float32)
        self._absmax = cur if self._absmax is None else (
            self.rate * self._absmax + (1 - self.rate) * cur)

    def scale(self) -> float:
        return (self.absmax or 0.0) / (2 ** (self.quant_bits - 1) - 1) or 1e-8

    def device_scale(self):
        if self._absmax is None:
            return jnp.float32(1e-8)
        return absmax_scale(self._absmax, self.quant_bits)


class HistObserver:
    """Percentile-of-histogram calibration (reference: observers/hist.py):
    clip scale at the `percent` mass point instead of the raw absmax —
    robust to activation outliers in PTQ."""

    def __init__(self, quant_bits=8, bins_count=2048, percent=0.999):
        self.quant_bits = quant_bits
        self.bins = bins_count
        self.percent = percent
        self._hist = np.zeros(bins_count)
        self._max = 1e-8

    def observe(self, x: Tensor):
        a = np.abs(np.asarray(x._value, np.float32)).ravel()
        m = float(a.max()) if a.size else 0.0
        if m > self._max:
            # rescale existing mass into the new range
            old_edges = np.linspace(0, self._max, self.bins + 1)
            new_edges = np.linspace(0, m, self.bins + 1)
            centers = (old_edges[:-1] + old_edges[1:]) / 2
            moved, _ = np.histogram(centers, new_edges, weights=self._hist)
            self._hist = moved
            self._max = m
        h, _ = np.histogram(a, np.linspace(0, self._max, self.bins + 1))
        self._hist += h

    def scale(self) -> float:
        total = self._hist.sum()
        if total == 0:
            return 1e-8
        cdf = np.cumsum(self._hist) / total
        cut = int(np.searchsorted(cdf, self.percent))
        clip = (cut + 1) / self.bins * self._max
        return clip / (2 ** (self.quant_bits - 1) - 1) or 1e-8


class AbsmaxChannelWiseObserver:
    """Per-output-channel weight absmax (reference:
    observers/abs_max_weight.py channel_wise quanter)."""

    def __init__(self, quant_bits=8, quant_axis=-1):
        self.quant_bits = quant_bits
        self.axis = quant_axis
        self._absmax = None

    def observe(self, x: Tensor):
        v = jnp.abs(x._value)
        axes = tuple(i for i in range(v.ndim) if i != self.axis % v.ndim)
        cur = jnp.max(v, axis=axes)
        self._absmax = cur if self._absmax is None else jnp.maximum(self._absmax, cur)

    def scale(self):
        return absmax_scale(self._absmax, self.quant_bits)

    device_scale = scale  # already a device array

    @classmethod
    def kv_page_scales(cls, values, quant_bits: int = 8,
                       qmax: float | None = None):
        """Per-slot-per-head absmax scales for the serving KV page pools:
        `values` is the [..., head_dim] K or V activation about to be
        scattered into quantized pages; head_dim is the reduced (channel)
        axis, exactly this observer's observe()+scale() math in one fused
        dispatch — serving and training quantization share ONE codepath
        (PR-16 satellite), and the result stays a device array so the
        decode path never host-syncs."""
        return absmax_scale(jnp.max(jnp.abs(values), axis=-1),
                            quant_bits, qmax=qmax)


class QuantConfig:
    """reference: quantization/config.py — global observer defaults with
    per-layer and per-type overrides."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation or AbsmaxObserver
        self.weight = weight or AbsmaxObserver
        self._types = (nn.Linear, nn.Conv2D)
        self._layer_overrides: dict[int, tuple] = {}
        self._type_overrides: dict[type, tuple] = {}

    def add_layer_config(self, layers, activation=None, weight=None):
        """Override observers for specific layer INSTANCES (reference
        config.py add_layer_config)."""
        if not isinstance(layers, (list, tuple)):
            layers = [layers]
        for l in layers:
            self._layer_overrides[id(l)] = (activation or self.activation,
                                            weight or self.weight)

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        for t in layer_types:
            self._type_overrides[t] = (activation or self.activation,
                                       weight or self.weight)
            if t not in self._types:
                self._types = self._types + (t,)

    def observers_for(self, layer):
        if id(layer) in self._layer_overrides:
            return self._layer_overrides[id(layer)]
        for t, pair in self._type_overrides.items():
            if isinstance(layer, t):
                return pair
        return (self.activation, self.weight)

    def quantable(self, layer):
        return isinstance(layer, self._types)


class FakeQuantLayer(Layer):
    def __init__(self, inner, config: QuantConfig):
        super().__init__()
        self.inner = inner
        act_cls, w_cls = config.observers_for(inner)
        self.w_observer = w_cls()
        self.a_observer = act_cls()
        self.w_observer.observe(inner.weight)

    def forward(self, x):
        self.a_observer.observe(x)
        # device_scale keeps the whole fake-quant step on device (observers
        # without one — HistObserver — fall back to the host float)
        a_scale = getattr(self.a_observer, "device_scale",
                          self.a_observer.scale)()
        w_scale = getattr(self.w_observer, "device_scale",
                          self.w_observer.scale)()
        xq = apply_op(lambda v: _fake_quant(v, a_scale), x, name="fake_quant")
        w = self.inner.weight
        wq = apply_op(lambda v: _fake_quant(v, w_scale), w, name="fake_quant")
        old = self.inner.weight._value
        self.inner.weight._set_value(wq._value)
        try:
            out = self.inner(xq)
        finally:
            self.inner.weight._set_value(old)
        return out


def _swap(model, config):
    for name, sub in list(model._sub_layers.items()):
        if config.quantable(sub):
            model._sub_layers[name] = FakeQuantLayer(sub, config)
        else:
            _swap(sub, config)
    return model


class QAT:
    """reference: quantization/qat.py."""

    def __init__(self, config: QuantConfig | None = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=False):
        return _swap(model, self.config)

    def convert(self, model, inplace=False):
        return model


class QuantedLinear(Layer):
    """Deploy-form linear: int8 weights + folded scale (reference
    nn/quant/qat/linear QuantedLinear / onnx-format conversion). Scale and
    bias are registered buffers so the converted model checkpoints whole."""

    def __init__(self, weight_i8, w_scale, bias=None):
        super().__init__()
        self.register_buffer("weight_quant", Tensor(weight_i8))
        self.register_buffer("w_scale", Tensor(jnp.asarray(w_scale, jnp.float32)))
        if bias is not None:
            b = bias._value if isinstance(bias, Tensor) else jnp.asarray(bias)
            self.register_buffer("bias", Tensor(b))
        else:
            self.bias = None

    def forward(self, x):
        return quanted_linear(x, self.weight_quant, self.w_scale._value, self.bias)


def _convert(model):
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, FakeQuantLayer) and isinstance(sub.inner, nn.Linear):
            scale = sub.w_observer.scale()
            w = sub.inner.weight._value
            sv = scale if np.ndim(scale) == 0 else jnp.asarray(scale)
            q = jnp.clip(jnp.round(w / sv), -127, 127).astype(jnp.int8)
            model._sub_layers[name] = QuantedLinear(
                q, sv, getattr(sub.inner, "bias", None))
        elif isinstance(sub, FakeQuantLayer):
            import warnings

            warnings.warn(
                f"PTQ.convert: no int8 deploy form for "
                f"{type(sub.inner).__name__}; keeping the fake-quant wrapper "
                f"(calibration preserved)")
        else:
            _convert(sub)
    return model


class PTQ:
    """reference: quantization/ptq.py — observe calibration batches, then
    `convert` folds scales into int8 deploy weights."""

    def __init__(self, config: QuantConfig | None = None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=False):
        return _swap(model, self.config)

    def convert(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        return _convert(model)


def quantize_weight_int8(w, quant_axis=-1):
    """Per-channel symmetric int8 weight quantization (the wo_int8 export
    path of `jit.save`): returns ``(q_int8, scale)`` with
    ``w ~= q.astype(f32) * scale`` and scale per `quant_axis` channel —
    computed through AbsmaxChannelWiseObserver so export calibration and
    QAT/PTQ share one absmax rule."""
    arr = jnp.asarray(np.asarray(w), jnp.float32)
    obs = AbsmaxChannelWiseObserver(quant_bits=8, quant_axis=quant_axis)
    obs.observe(Tensor(arr))
    scale = obs.scale()  # [channels], >= 1e-8
    shape = [1] * arr.ndim
    shape[quant_axis % arr.ndim] = -1
    sc = jnp.reshape(scale, shape)
    q = jnp.clip(jnp.round(arr / sc), -127, 127).astype(jnp.int8)
    return np.asarray(q), np.asarray(scale, np.float32)


def quanted_linear(x, weight, w_scale, bias=None):
    """int8 weight x bf16 activation matmul (deploy path)."""

    def f(v, w, *b):
        out = jnp.matmul(v, w.astype(v.dtype)) * w_scale
        if b:
            out = out + b[0]
        return out

    args = [x, weight] + ([bias] if bias is not None else [])
    return apply_op(f, *args, name="quanted_linear")
