"""paddle.reader (reference: legacy python reader decorators — map_readers,
buffered, compose, chain, shuffle, firstn). Kept for source parity with
older training scripts; paddle.io.DataLoader is the modern path."""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle", "firstn",
           "cache"]


def map_readers(func, *readers):
    def reader():
        its = [r() for r in readers]
        for items in zip(*its):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        _random.shuffle(buf)
        yield from buf

    return shuffled


def chain(*readers):
    def chained():
        return itertools.chain(*[r() for r in readers])

    return chained


def compose(*readers, check_alignment=True):
    def composed():
        its = [r() for r in readers]
        for items in zip(*its):
            out = []
            for it in items:
                if isinstance(it, tuple):
                    out.extend(it)
                else:
                    out.append(it)
            yield tuple(out)

    return composed


def buffered(reader, size):
    """Prefetch up to `size` items on a background thread."""

    class _End:
        pass

    def buffered_reader():
        q: _queue.Queue = _queue.Queue(maxsize=size)

        def fill():
            try:
                for item in reader():
                    q.put(item)
            finally:
                q.put(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _End:
                break
            yield item

    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def cache(reader):
    all_items = []
    filled = [False]

    def cached():
        if not filled[0]:
            all_items.extend(reader())
            filled[0] = True
        return iter(all_items)

    return cached
