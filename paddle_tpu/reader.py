"""paddle.reader (reference: legacy python reader decorators — map_readers,
buffered, compose, chain, shuffle, firstn). Kept for source parity with
older training scripts; paddle.io.DataLoader is the modern path."""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle", "firstn",
           "cache", "ComposeNotAligned"]


class ComposeNotAligned(ValueError):
    """compose(check_alignment=True): the composed readers ended at
    different positions (zip would silently truncate to the shortest)."""


def map_readers(func, *readers):
    def reader():
        its = [r() for r in readers]
        for items in zip(*its):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        _random.shuffle(buf)
        yield from buf

    return shuffled


def chain(*readers):
    def chained():
        return itertools.chain(*[r() for r in readers])

    return chained


def compose(*readers, check_alignment=True):
    _end = object()

    def composed():
        its = [r() for r in readers]
        while True:
            items = [next(it, _end) for it in its]
            ended = sum(1 for i in items if i is _end)
            if ended:
                if check_alignment and ended != len(items):
                    raise ComposeNotAligned(
                        f"compose: {ended}/{len(items)} readers ended early "
                        "(streams are misaligned)")
                return
            out = []
            for it in items:
                if isinstance(it, tuple):
                    out.extend(it)
                else:
                    out.append(it)
            yield tuple(out)

    return composed


def buffered(reader, size):
    """Prefetch up to `size` items on a background thread. A producer
    exception is captured and RE-RAISED in the consumer (the DeviceFeeder
    contract) — it must not masquerade as a short stream."""

    class _End:
        pass

    def buffered_reader():
        from paddle_tpu.io.device_feed import (THREAD_PREFIX,
                                               interruptible_put,
                                               stop_and_join)

        q: _queue.Queue = _queue.Queue(maxsize=size)
        stop = threading.Event()
        err: list = []

        def fill():
            try:
                for item in reader():
                    # interruptible: an abandoned consumer sets `stop` from
                    # its generator-close finally, unblocking a producer
                    # parked on a full queue
                    if not interruptible_put(q, item, stop):
                        return
            except BaseException as e:
                err.append(e)
            finally:
                interruptible_put(q, _End, stop)

        t = threading.Thread(target=fill, daemon=True,
                             name=f"{THREAD_PREFIX}.buffered")
        t.start()
        try:
            while True:
                item = q.get()
                if item is _End:
                    if err:
                        raise err[0]
                    break
                yield item
        finally:
            stop_and_join(q, stop, t)

    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def cache(reader):
    all_items = []
    filled = [False]

    def cached():
        if not filled[0]:
            all_items.extend(reader())
            filled[0] = True
        return iter(all_items)

    return cached
