"""paddle.regularizer parity (reference: python/paddle/regularizer.py).
The coefficient objects optimizers read via their weight_decay parameter."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class L2Decay:
    """Weight decay coefficient holder (optimizers read `_coeff`)."""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __repr__(self):
        return f"L2Decay({self._coeff})"


class L1Decay:
    """L1 regularization: optimizers add coeff * sign(p) to the gradient."""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
        self._l1 = True

    @property
    def coeff(self):
        return self._coeff

    def __repr__(self):
        return f"L1Decay({self._coeff})"
