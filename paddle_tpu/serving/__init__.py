"""paddle_tpu.serving — paged-KV-cache continuous-batching LLM serving.

The inference-side counterpart of the training runtimes (ROADMAP item 1):
`ServingEngine` drives iteration-level batching over a block-granular KV
cache with a Pallas ragged decode-attention kernel
(paddle_tpu.ops.pallas.paged_attention). See docs/serving.md.

The fleet front (ROADMAP item 4, docs/router.md): `Router` dispatches
over N replicas behind the `replica.py` transport seam — health-aware
placement with session-affinity rendezvous hashing, circuit breaking +
draining, bounded failover re-dispatch, and admission control/shedding
under overload. `InProcessReplica` is the CI-grade transport (engine +
driver thread in-process); real deployments speak the same three-method
protocol over HTTP/RPC against serve.py's /healthz + /stats + /generate.
"""
from paddle_tpu.serving.drafts import NGramProposer
from paddle_tpu.serving.engine import ServingConfig, ServingEngine
from paddle_tpu.serving.kv_cache import (PageAllocator, kv_page_bytes,
                                         pages_for_budget)
from paddle_tpu.serving.replica import (InProcessReplica, ReplicaDead,
                                        ReplicaError, ReplicaStream,
                                        StreamCut, StreamGap)
from paddle_tpu.serving.router import (Router, RouterConfig, backoff_delays,
                                       rendezvous_order)
from paddle_tpu.serving.sampling import request_key, sample_tokens
from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                          QueueFull, Request, RequestState)

__all__ = ["ServingConfig", "ServingEngine", "NGramProposer",
           "PageAllocator",
           "kv_page_bytes", "pages_for_budget", "sample_tokens",
           "request_key", "ContinuousBatchingScheduler", "Request",
           "RequestState", "QueueFull", "Router", "RouterConfig",
           "rendezvous_order", "backoff_delays", "InProcessReplica",
           "ReplicaError", "ReplicaDead", "ReplicaStream", "StreamCut",
           "StreamGap"]
