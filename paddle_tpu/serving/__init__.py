"""paddle_tpu.serving — paged-KV-cache continuous-batching LLM serving.

The inference-side counterpart of the training runtimes (ROADMAP item 1):
`ServingEngine` drives iteration-level batching over a block-granular KV
cache with a Pallas ragged decode-attention kernel
(paddle_tpu.ops.pallas.paged_attention). See docs/serving.md.
"""
from paddle_tpu.serving.engine import ServingConfig, ServingEngine
from paddle_tpu.serving.kv_cache import (PageAllocator, kv_page_bytes,
                                         pages_for_budget)
from paddle_tpu.serving.sampling import request_key, sample_tokens
from paddle_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                          Request, RequestState)

__all__ = ["ServingConfig", "ServingEngine", "PageAllocator",
           "kv_page_bytes", "pages_for_budget", "sample_tokens",
           "request_key", "ContinuousBatchingScheduler", "Request",
           "RequestState"]
