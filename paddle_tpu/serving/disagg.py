"""Disaggregated prefill/decode: prefill workers + the KV-page handoff.

The split (the Splitwise/DistServe serving shape): a DECODE-role engine
admits a request — allocating its page chain in its own pool — and POSTS
a `PrefillJob` to the `HandoffChannel` instead of prefilling inline. A
`PrefillWorker` thread drains the channel, packs the waiting prompts
into ONE ``[1, frame]`` segment-id flash frame (`ServingEngine`'s packed
prefill program — first-fit over 32-aligned rows), runs the device work,
and delivers a typed `KVHandoff` back; the decode side ingests it and
activates the request. Decode steps never stall behind prefill chunks,
and one program dispatch amortizes over N short prompts.

Two handoff modes:

  * ``alias`` (single host, the default): worker and decode engine share
    ONE page pool, so the prefill writes land directly in the pages the
    decode side already allocated — the handoff carries no bytes, it is
    a page-table splice (the decode side just activates). Device work is
    serialized through the engine's step lock because every compiled
    step reassigns (and on TPU donates) the functional cache handle.
  * ``copy``: the worker owns a small side pool and allocator, prefills
    there, extracts each page through the engine's compiled one-page
    gather, and the decode side splices the bytes into its chain through
    the compiled one-page restore — the page-granular device-to-device
    copy program pair (PR-16's demote/promote shape), which is exactly
    what a cross-host transport would stream.

Exactly-once recovery: a job whose worker died, whose handoff was
dropped, or whose handoff is overdue is RECLAIMED — the decode side
re-prefills locally into the same chain. Page writes are idempotent
byte-overwrites into pages the request owns either way, so a worker
killed mid-handoff (``serving.prefill.kill``) or a dropped delivery
(``serving.handoff.drop``) yields streams bit-equal to fault-free:
zero lost, zero double-activated (`_pending_handoff` is popped exactly
once, on the single decode thread).

Chaos points (PR-10 registry):

  * ``serving.prefill.kill``  — raises on the worker thread BETWEEN the
    device prefill and the handoff delivery (mid-handoff): the worker
    dies, its in-flight jobs mark failed, decode reclaims.
  * ``serving.handoff.drop``  — silently discards one delivered handoff:
    the decode side must time out and reclaim, never wedge.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from paddle_tpu.distributed.resilience import faults
from paddle_tpu.observability import events as obs_events
from paddle_tpu.serving.kv_cache import PageAllocator

__all__ = ["PrefillJob", "KVHandoff", "HandoffChannel", "PrefillWorker",
           "build_disagg"]

faults.register(
    "serving.prefill.kill",
    "kills a prefill worker thread mid-handoff (after the device prefill, "
    "before the KVHandoff delivery): its in-flight jobs mark failed and "
    "the decode side must reclaim by re-prefilling locally — exactly-once "
    "streams, bit-equal to fault-free")
faults.register(
    "serving.handoff.drop",
    "silently discards one delivered KV-page handoff: the decode side "
    "must detect the overdue job (serving_handoff_timeout_s) and reclaim "
    "by re-prefilling locally, never wedge a stream")


@dataclass
class PrefillJob:
    """One posted prefill: the request's full prompt context plus the
    page chain the decode side already allocated for it (a snapshot row
    — allocator mutations stay on the decode thread)."""
    rid: int
    tokens: np.ndarray            # int32 [L] full context to prefill
    page_row: np.ndarray          # int32 [pages_per_seq] chain snapshot
    posted_t: float
    trace_id: str = ""
    cancelled: bool = False       # set by decode: skip if not yet started
    failed: bool = False          # set by a dying worker: reclaim me


@dataclass
class KVHandoff:
    """One finished prefill, worker -> decode. ``pages`` is None in
    alias mode (the bytes are already in the shared pool; the handoff is
    the activation itself) or the per-page pool slices in copy mode."""
    rid: int
    n_pages: int
    ms: float                     # device ms attributed to this job
    worker: str
    pages: list | None = None     # copy mode: [{pool_name: np[...]}]


class HandoffChannel:
    """The decode<->prefill seam: a job queue (decode posts, workers
    take) and a done queue (workers deliver, decode drains). Plain
    condition-variable queues — no pickling, no sockets; a cross-host
    deployment would put a transport behind this same four-method
    surface."""

    def __init__(self):
        self._lock = threading.Lock()
        self._jobs: deque = deque()
        self._done: deque = deque()
        self._jobs_cv = threading.Condition(self._lock)
        self._done_cv = threading.Condition(self._lock)
        self._workers: list = []
        self.posted = 0
        self.delivered = 0
        self.dropped = 0

    # ---- decode side -------------------------------------------------
    def post(self, job: PrefillJob):
        with self._lock:
            self._jobs.append(job)
            self.posted += 1
            self._jobs_cv.notify()

    def take_done(self, wait_s: float = 0.0) -> list:
        with self._lock:
            if not self._done and wait_s > 0:
                self._done_cv.wait(wait_s)
            out = list(self._done)
            self._done.clear()
            return out

    # ---- worker side -------------------------------------------------
    def take_jobs(self, max_jobs: int, timeout_s: float = 0.02) -> list:
        with self._lock:
            if not self._jobs:
                self._jobs_cv.wait(timeout_s)
            out = []
            while self._jobs and len(out) < max_jobs:
                job = self._jobs.popleft()
                if not job.cancelled:
                    out.append(job)
            return out

    def deliver(self, handoff: KVHandoff):
        if faults.fire_check("serving.handoff.drop"):
            # the chaos contract: the handoff vanishes in transit; the
            # decode side must reclaim on timeout, never wedge
            self.dropped += 1
            obs_events.emit("serving", "handoff_drop", severity="warn",
                            rid=int(handoff.rid), worker=handoff.worker)
            return
        with self._lock:
            self._done.append(handoff)
            self.delivered += 1
            self._done_cv.notify()

    # ---- worker registry ---------------------------------------------
    def register_worker(self, worker: "PrefillWorker"):
        with self._lock:
            self._workers.append(worker)

    def workers_alive(self) -> bool:
        return any(w.alive for w in list(self._workers))

    def stats(self) -> dict:
        with self._lock:
            return {"posted": self.posted, "delivered": self.delivered,
                    "dropped": self.dropped, "queued": len(self._jobs),
                    "workers": len(self._workers),
                    "workers_alive": sum(w.alive for w in self._workers)}


_worker_seq = itertools.count()


class PrefillWorker:
    """One prefill worker thread draining a `HandoffChannel` into an
    engine's packed-prefill program. ``mode="alias"`` writes straight
    into the decode engine's shared pools under its step lock;
    ``mode="copy"`` prefills a private side pool and ships page bytes
    through the compiled extract program."""

    def __init__(self, engine, channel: HandoffChannel, *,
                 mode: str = "alias", max_jobs: int = 0, name: str = ""):
        if mode not in ("alias", "copy"):
            raise ValueError(f"handoff mode must be alias/copy, "
                             f"got {mode!r}")
        self.engine = engine
        self.channel = channel
        self.mode = mode
        self.max_jobs = int(max_jobs or engine.decode_batch)
        self.name = name or f"w{next(_worker_seq)}"
        self.alive = True
        self.dead_cause: str | None = None
        self._stop = False
        self._current: list = []
        if mode == "copy":
            # a side pool just big enough for one taken batch of packed
            # frames (+ the reserved null page) — the worker's private
            # staging memory, freed job by job after extraction
            ps = engine.page_size
            side_pages = 1 + self.max_jobs * -(-engine.pack_frame // ps)
            self._alloc = PageAllocator(side_pages, ps)
            shape = (engine.num_layers, engine.num_kv_heads, side_pages,
                     ps, engine.head_dim)
            self._cache = {"k": jnp.zeros(shape, engine.kv_dtype),
                           "v": jnp.zeros(shape, engine.kv_dtype)}
            if engine.kv_quantized:
                self._cache["k_scale"] = jnp.zeros(shape[:4], jnp.float32)
                self._cache["v_scale"] = jnp.zeros(shape[:4], jnp.float32)
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"paddle_tpu.serving.prefill.{self.name}")
        channel.register_worker(self)
        self._thread.start()

    def _run(self):
        try:
            while not self._stop:
                jobs = self.channel.take_jobs(self.max_jobs,
                                              timeout_s=0.02)
                if jobs:
                    self._process(jobs)
        except BaseException as e:  # noqa: BLE001 — the worker's corpse
            # must be observable: failed jobs reclaim, probes see a dead
            # worker, the channel stops being post-worthy
            self.dead_cause = f"{type(e).__name__}: {e}"
            for job in self._current:
                job.failed = True
            obs_events.emit("serving", "prefill_worker_died",
                            severity="error", worker=self.name,
                            cause=self.dead_cause,
                            jobs_failed=len(self._current))
        finally:
            self.alive = False

    def _process(self, jobs: list):
        # _current stays set across an exception so the death handler in
        # _run can mark exactly these jobs failed (the reclaim trigger)
        self._current = jobs
        if self.mode == "alias":
            ms = self.engine.prefill_jobs(jobs)
            # mid-handoff: the device writes are done, the handoffs
            # are not delivered — the exactly-once window
            faults.point("serving.prefill.kill")
            per = ms / max(len(jobs), 1)
            ps = self.engine.page_size
            for job in jobs:
                self.channel.deliver(KVHandoff(
                    rid=job.rid,
                    n_pages=-(-int(job.tokens.size) // ps),
                    ms=per, worker=self.name))
        else:
            payloads, ms = self._prefill_copy(jobs)
            faults.point("serving.prefill.kill")
            for job, pages in zip(jobs, payloads):
                self.channel.deliver(KVHandoff(
                    rid=job.rid, n_pages=len(pages), ms=ms,
                    worker=self.name, pages=pages))
        self._current = []

    def _prefill_copy(self, jobs: list):
        """Copy mode: prefill the jobs' prompts into the private side
        pool (same packed frames), then extract each page's bytes
        through the engine's compiled one-page gather. The engine's
        step lock serializes the shared compiled programs' device use
        against the decode loop."""
        eng = self.engine
        ps = eng.page_size
        t0 = time.perf_counter()
        with eng._step_lock:
            keys = []
            for job in jobs:
                key = ("prefill_worker", self.name, job.rid)
                if not self._alloc.ensure(key, int(job.tokens.size)):
                    raise RuntimeError(
                        f"prefill worker side pool too small for "
                        f"{int(job.tokens.size)}-token job")
                keys.append(key)
            items = [(job.tokens,
                      self._alloc.page_table_row(key, eng.pages_per_seq))
                     for job, key in zip(jobs, keys)]
            for frame in eng._plan_frames(items, lambda it: it[0].size):
                self._cache = eng.packed_prefill_cache(self._cache, frame)
            extract = eng._extract_page()
            payloads = []
            for job, key in zip(jobs, keys):
                chain = self._alloc.chain(key)
                chain = chain[:-(-int(job.tokens.size) // ps)]
                pages = []
                for page in chain:
                    data = extract(self._cache,
                                   jnp.asarray(page, jnp.int32))
                    pages.append({name: np.asarray(a)
                                  for name, a in data.items()})
                payloads.append(pages)
                self._alloc.free_request(key)
        ms = (time.perf_counter() - t0) * 1e3 / max(len(jobs), 1)
        return payloads, ms

    def close(self):
        self._stop = True
        self._thread.join(timeout=5.0)
        self.alive = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def build_disagg(engine, n_workers: int = 1, *, mode: str = "alias",
                 timeout_s: float | None = None):
    """Convenience wiring: attach a fresh `HandoffChannel` to `engine`
    (which becomes the decode side regardless of its configured role)
    and start `n_workers` prefill workers against it. Returns
    ``(channel, [workers])``; callers own worker close()."""
    channel = HandoffChannel()
    engine.attach_prefill(channel, timeout_s=timeout_s)
    workers = [PrefillWorker(engine, channel, mode=mode)
               for _ in range(n_workers)]
    return channel, workers
