"""Self-drafting n-gram proposer for speculative decoding (host side).

No second model: each request carries a suffix-match table built from its
OWN committed stream (prompt + generated tokens). An order-n entry maps
the last n committed tokens to the token that followed them the last time
that n-gram appeared; proposing K drafts walks the tables greedily,
highest order first, simulating its own extensions so a whole predicted
run (a loop, a copied span, boilerplate) drafts in one step. The verify
pass makes correctness unconditional — a bad draft costs nothing but its
slot in the [batch, K+1] frame — so the proposer optimizes HIT RATE only:
latest occurrence wins (adapts to phase changes), and a miss falls back to
repeating the last token (cheap, and right for degenerate loops).

Cost per committed token is O(max_order) dict updates; per step,
O(K * max_order) lookups — microseconds against a decode dispatch, and
measured anyway (`draft_ms`) so the bench can report draft overhead
honestly.
"""
from __future__ import annotations

import numpy as np

__all__ = ["NGramProposer"]


class NGramProposer:
    """Per-request suffix-match draft tables. `max_order` bounds the n-gram
    length (longest-match-first lookup); `min_order` >= 1."""

    def __init__(self, max_order: int = 3, min_order: int = 1):
        if not 1 <= min_order <= max_order:
            raise ValueError(f"need 1 <= min_order <= max_order, got "
                             f"{min_order}..{max_order}")
        self.max_order = int(max_order)
        self.min_order = int(min_order)
        # rid -> (tables per order, rolling suffix of the committed stream)
        self._state: dict[int, tuple[list[dict], list[int]]] = {}

    # ---- stream maintenance ----------------------------------------------
    def add_request(self, rid: int, tokens) -> None:
        """(Re)seed `rid`'s tables from its committed stream — the prompt
        at submission, or prompt + generated on an eviction re-prefill
        (idempotent: tables are a pure function of the stream)."""
        tables = [dict() for _ in range(self.max_order)]
        toks = [int(t) for t in np.asarray(tokens).reshape(-1)]
        for i in range(1, len(toks)):
            self._observe_into(tables, toks[:i], toks[i])
        self._state[rid] = (tables, toks[-self.max_order:])

    def _observe_into(self, tables, prefix, nxt):
        for order in range(self.min_order, self.max_order + 1):
            if len(prefix) >= order:
                tables[order - 1][tuple(prefix[-order:])] = nxt

    def observe(self, rid: int, token: int) -> None:
        """Fold one committed token into `rid`'s tables."""
        state = self._state.get(rid)
        if state is None:
            return
        tables, suffix = state
        self._observe_into(tables, suffix, int(token))
        suffix.append(int(token))
        del suffix[:-self.max_order]

    def drop(self, rid: int) -> None:
        self._state.pop(rid, None)

    # ---- proposal ---------------------------------------------------------
    def propose(self, rid: int, k: int) -> list[int]:
        """K draft tokens continuing `rid`'s committed stream: per draft,
        the longest-order table hit on the (simulated) suffix, else repeat
        the last token. Always returns exactly k valid token ids."""
        state = self._state.get(rid)
        if state is None or k <= 0:
            return [0] * max(k, 0)
        tables, suffix = state
        sim = list(suffix)
        out = []
        for _ in range(k):
            nxt = None
            for order in range(min(self.max_order, len(sim)),
                               self.min_order - 1, -1):
                nxt = tables[order - 1].get(tuple(sim[-order:]))
                if nxt is not None:
                    break
            if nxt is None:
                nxt = sim[-1] if sim else 0
            out.append(nxt)
            sim.append(nxt)
        return out
